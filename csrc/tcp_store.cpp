// TCPStore: TCP key-value rendezvous for multi-host bootstrap.
//
// Native C++ equivalent of the reference's store
// (reference: paddle/phi/core/distributed/store/tcp_store.h:121 —
// MasterDaemon accept loop + per-connection command dispatch;
// store/socket.cpp). Used over DCN to exchange coordinator addresses /
// ranks before any ICI communication exists (the NCCL-unique-id exchange
// role; here it bootstraps jax.distributed / multi-host meshes).
//
// Protocol (little-endian, length-prefixed):
//   cmd u8:  1=SET  2=GET(wait)  3=ADD  4=WAIT  5=CHECK  6=DELETE
//   key:     u32 len + bytes;  value: u32 len + bytes (SET reply: u8 1)
//   GET/WAIT block server-side (condvar) until the key exists or the
//   client-supplied timeout_ms elapses (reply vlen=0xFFFFFFFF on timeout).
//   ADD: i64 delta -> i64 new value (atomic counter, used for barriers).
//
// Exposed through a C ABI (ctypes; pybind11 is unavailable in this
// image) — see python wrapper distributed/store.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::map<std::string, std::vector<uint8_t>> data;
  std::mutex mu;
  std::condition_variable cv;
};

struct Server {
  int listen_fd = -1;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;
  std::mutex conns_mu;
  Store store;
  ~Server() {
    stop.store(true);
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    // Wake every serve_conn thread: those parked in cv.wait_for (GET/WAIT)
    // observe stop via the predicate; those blocked in recv() get EOF from
    // the socket shutdown. Without both, join() below can hang for the
    // full client timeout (900s default).
    store.cv.notify_all();
    // Join the accept thread first (listen_fd is already shut down, so it
    // exits promptly) — after this no new conn threads can be registered.
    if (accept_thread.joinable()) accept_thread.join();
    // Swap the thread list out under the lock, then join WITHOUT holding
    // conns_mu: serve_conn must take conns_mu to erase its fd on exit, so
    // joining while holding it deadlocks against any live connection.
    std::vector<std::thread> to_join;
    {
      std::lock_guard<std::mutex> g(conns_mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
      to_join.swap(conns);
    }
    for (auto& t : to_join)
      if (t.joinable()) t.join();
  }
};

// Upper bound on any key/value frame. Object collectives ship pickled
// host metadata through the store, so this is generous — but bounded, so
// a garbage frame from a stray client can't force a multi-GiB allocation
// on the coordinator.
constexpr uint32_t kMaxBlob = 64u * 1024 * 1024;

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_blob(int fd, std::string* out) {
  uint32_t len;
  if (!read_full(fd, &len, 4)) return false;
  if (len > kMaxBlob) return false;  // drop connection on oversized frame
  out->resize(len);
  return len == 0 || read_full(fd, &(*out)[0], len);
}

void serve_conn(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t cmd;
    if (!read_full(fd, &cmd, 1)) break;
    std::string key;
    if (!read_blob(fd, &key)) break;
    if (cmd == 1) {  // SET
      std::string val;
      if (!read_blob(fd, &val)) break;
      {
        std::lock_guard<std::mutex> g(s->store.mu);
        s->store.data[key].assign(val.begin(), val.end());
      }
      s->store.cv.notify_all();
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
    } else if (cmd == 2 || cmd == 4) {  // GET / WAIT
      int64_t timeout_ms;
      if (!read_full(fd, &timeout_ms, 8)) break;
      std::unique_lock<std::mutex> lk(s->store.mu);
      bool ok = s->store.cv.wait_for(
          lk, std::chrono::milliseconds(timeout_ms),
          [&] { return s->store.data.count(key) > 0 || s->stop.load(); });
      if (!ok || s->stop.load()) {
        lk.unlock();
        uint32_t miss = 0xFFFFFFFFu;
        if (!write_full(fd, &miss, 4)) break;
        continue;
      }
      std::vector<uint8_t> val = s->store.data[key];
      lk.unlock();
      if (cmd == 4) {
        uint32_t zero = 0;  // WAIT replies empty blob on success
        if (!write_full(fd, &zero, 4)) break;
      } else {
        uint32_t len = static_cast<uint32_t>(val.size());
        if (!write_full(fd, &len, 4)) break;
        if (len && !write_full(fd, val.data(), len)) break;
      }
    } else if (cmd == 3) {  // ADD
      int64_t delta, cur = 0;
      if (!read_full(fd, &delta, 8)) break;
      {
        std::lock_guard<std::mutex> g(s->store.mu);
        auto& v = s->store.data[key];
        if (v.size() == 8) std::memcpy(&cur, v.data(), 8);
        cur += delta;
        v.resize(8);
        std::memcpy(v.data(), &cur, 8);
      }
      s->store.cv.notify_all();
      if (!write_full(fd, &cur, 8)) break;
    } else if (cmd == 5) {  // CHECK
      uint8_t present;
      {
        std::lock_guard<std::mutex> g(s->store.mu);
        present = s->store.data.count(key) ? 1 : 0;
      }
      if (!write_full(fd, &present, 1)) break;
    } else if (cmd == 6) {  // DELETE
      {
        std::lock_guard<std::mutex> g(s->store.mu);
        s->store.data.erase(key);
      }
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
    } else {
      break;
    }
  }
  {
    // drop our fd from the shutdown list BEFORE closing: the number can
    // be reused by an unrelated descriptor, and ~Server must not
    // shutdown() that one
    std::lock_guard<std::mutex> g(s->conns_mu);
    for (auto it = s->conn_fds.begin(); it != s->conn_fds.end(); ++it) {
      if (*it == fd) {
        s->conn_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

}  // namespace

extern "C" {

// ---- server -------------------------------------------------------------
void* tcpstore_server_start(int port, int* bound_port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (bound_port) *bound_port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] {
    while (!s->stop.load()) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> g(s->conns_mu);
      s->conn_fds.push_back(fd);
      s->conns.emplace_back(serve_conn, s, fd);
    }
  });
  return s;
}

void tcpstore_server_stop(void* handle) {
  delete static_cast<Server*>(handle);
}

// ---- client -------------------------------------------------------------
int tcpstore_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

void tcpstore_close(int fd) { ::close(fd); }

static bool send_key(int fd, uint8_t cmd, const char* key) {
  uint32_t klen = static_cast<uint32_t>(std::strlen(key));
  return write_full(fd, &cmd, 1) && write_full(fd, &klen, 4) &&
         write_full(fd, key, klen);
}

int tcpstore_set(int fd, const char* key, const uint8_t* val, uint32_t vlen) {
  if (!send_key(fd, 1, key)) return -1;
  if (!write_full(fd, &vlen, 4)) return -1;
  if (vlen && !write_full(fd, val, vlen)) return -1;
  uint8_t ok;
  return read_full(fd, &ok, 1) ? 0 : -1;
}

// Returns value length, or -1 on timeout/error. Caller frees *out with
// tcpstore_free.
int64_t tcpstore_get(int fd, const char* key, int64_t timeout_ms,
                     uint8_t** out) {
  if (!send_key(fd, 2, key)) return -1;
  if (!write_full(fd, &timeout_ms, 8)) return -1;
  uint32_t len;
  if (!read_full(fd, &len, 4)) return -1;
  if (len == 0xFFFFFFFFu) return -1;
  *out = static_cast<uint8_t*>(::malloc(len ? len : 1));
  if (len && !read_full(fd, *out, len)) {
    ::free(*out);
    return -1;
  }
  return static_cast<int64_t>(len);
}

void tcpstore_free(uint8_t* p) { ::free(p); }

int64_t tcpstore_add(int fd, const char* key, int64_t delta) {
  if (!send_key(fd, 3, key)) return INT64_MIN;
  if (!write_full(fd, &delta, 8)) return INT64_MIN;
  int64_t cur;
  return read_full(fd, &cur, 8) ? cur : INT64_MIN;
}

int tcpstore_wait(int fd, const char* key, int64_t timeout_ms) {
  if (!send_key(fd, 4, key)) return -1;
  if (!write_full(fd, &timeout_ms, 8)) return -1;
  uint32_t len;
  if (!read_full(fd, &len, 4)) return -1;
  return len == 0xFFFFFFFFu ? -1 : 0;
}

int tcpstore_check(int fd, const char* key) {
  if (!send_key(fd, 5, key)) return -1;
  uint8_t present;
  return read_full(fd, &present, 1) ? present : -1;
}

int tcpstore_delete(int fd, const char* key) {
  if (!send_key(fd, 6, key)) return -1;
  uint8_t ok;
  return read_full(fd, &ok, 1) ? 0 : -1;
}

}  // extern "C"
