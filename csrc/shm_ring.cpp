// Shared-memory ring buffer for the multiprocess data loader.
//
// Native C++ equivalent of the reference's shared-memory dataloader
// transport (reference: paddle/fluid/imperative/data_loader.cc —
// _shared_memory tensor path + paddle/fluid/memory/allocation shm;
// python side io/dataloader/dataloader_iter.py:358 worker loop).
//
// Worker processes serialize batches into a POSIX shm segment holding a
// bounded byte ring guarded by process-shared pthread mutex/condvars —
// the parent reads whole records without pipes or pickled fd passing.
// Records are length-prefixed; writers block when the ring is full,
// readers when empty (with timeouts so a dead peer can't hang training —
// the watchdog role of the reference's CommTaskManager, host-side).

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>

namespace {

struct RingHeader {
  pthread_mutex_t mu;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
  uint64_t capacity;  // data bytes
  uint64_t head;      // read position (absolute, monotonically increasing)
  uint64_t tail;      // write position
  uint32_t closed;
};

struct Ring {
  RingHeader* hdr = nullptr;
  uint8_t* data = nullptr;
  uint64_t map_size = 0;
  std::string name;
  bool owner = false;
};

void abs_deadline(timespec* ts, int64_t timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// Acquire the process-shared mutex with a deadline. The mutex is ROBUST:
// if a worker dies while holding it we get EOWNERDEAD, mark the state
// consistent, and carry on — a killed peer must not hang training.
// Returns 0 on success, -1 on timeout/unrecoverable.
int lock_robust(RingHeader* hdr, const timespec* ts) {
  int rc = pthread_mutex_timedlock(&hdr->mu, ts);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&hdr->mu);
    // A writer may have died mid-record; the ring byte-counters are only
    // advanced after a full copy, so the shared state is still coherent.
    rc = 0;
  }
  return rc == 0 ? 0 : -1;
}

void copy_in(Ring* r, uint64_t pos, const uint8_t* src, uint64_t n) {
  uint64_t off = pos % r->hdr->capacity;
  uint64_t first = r->hdr->capacity - off;
  if (first >= n) {
    std::memcpy(r->data + off, src, n);
  } else {
    std::memcpy(r->data + off, src, first);
    std::memcpy(r->data, src + first, n - first);
  }
}

void copy_out(Ring* r, uint64_t pos, uint8_t* dst, uint64_t n) {
  uint64_t off = pos % r->hdr->capacity;
  uint64_t first = r->hdr->capacity - off;
  if (first >= n) {
    std::memcpy(dst, r->data + off, n);
  } else {
    std::memcpy(dst, r->data + off, first);
    std::memcpy(dst + first, r->data, n - first);
  }
}

}  // namespace

extern "C" {

// Create a named ring with `capacity` data bytes. Returns handle or null.
void* shmring_create(const char* name, uint64_t capacity) {
  ::shm_unlink(name);
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(RingHeader) + capacity;
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
  auto* r = new Ring();
  r->hdr = static_cast<RingHeader*>(mem);
  r->data = static_cast<uint8_t*>(mem) + sizeof(RingHeader);
  r->map_size = total;
  r->name = name;
  r->owner = true;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&r->hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&r->hdr->not_full, &ca);
  pthread_cond_init(&r->hdr->not_empty, &ca);
  r->hdr->capacity = capacity;
  r->hdr->head = 0;
  r->hdr->tail = 0;
  r->hdr->closed = 0;
  return r;
}

void* shmring_attach(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* r = new Ring();
  r->hdr = static_cast<RingHeader*>(mem);
  r->data = static_cast<uint8_t*>(mem) + sizeof(RingHeader);
  r->map_size = static_cast<uint64_t>(st.st_size);
  r->name = name;
  r->owner = false;
  return r;
}

// 0 ok; -1 timeout; -2 closed; -3 record larger than ring.
int shmring_write(void* handle, const uint8_t* buf, uint64_t len,
                  int64_t timeout_ms) {
  auto* r = static_cast<Ring*>(handle);
  uint64_t need = len + 8;
  if (need > r->hdr->capacity) return -3;
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  if (lock_robust(r->hdr, &ts) != 0) return -1;
  while (r->hdr->tail + need - r->hdr->head > r->hdr->capacity &&
         !r->hdr->closed) {
    int rc = pthread_cond_timedwait(&r->hdr->not_full, &r->hdr->mu, &ts);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&r->hdr->mu);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&r->hdr->mu);
      return -1;
    }
  }
  if (r->hdr->closed) {
    pthread_mutex_unlock(&r->hdr->mu);
    return -2;
  }
  uint64_t len64 = len;
  copy_in(r, r->hdr->tail, reinterpret_cast<uint8_t*>(&len64), 8);
  copy_in(r, r->hdr->tail + 8, buf, len);
  r->hdr->tail += need;
  pthread_cond_signal(&r->hdr->not_empty);
  pthread_mutex_unlock(&r->hdr->mu);
  return 0;
}

// Returns record length (>=0) with *out malloc'd; -1 timeout; -2 closed
// and drained.
int64_t shmring_read(void* handle, uint8_t** out, int64_t timeout_ms) {
  auto* r = static_cast<Ring*>(handle);
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  if (lock_robust(r->hdr, &ts) != 0) return -1;
  while (r->hdr->head == r->hdr->tail && !r->hdr->closed) {
    int rc = pthread_cond_timedwait(&r->hdr->not_empty, &r->hdr->mu, &ts);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&r->hdr->mu);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&r->hdr->mu);
      return -1;
    }
  }
  if (r->hdr->head == r->hdr->tail && r->hdr->closed) {
    pthread_mutex_unlock(&r->hdr->mu);
    return -2;
  }
  uint64_t len64;
  copy_out(r, r->hdr->head, reinterpret_cast<uint8_t*>(&len64), 8);
  if (len64 > r->hdr->capacity - 8) {  // corrupt header — fail loudly
    r->hdr->closed = 1;
    pthread_cond_broadcast(&r->hdr->not_full);
    pthread_cond_broadcast(&r->hdr->not_empty);  // wake blocked readers too
    pthread_mutex_unlock(&r->hdr->mu);
    return -2;
  }
  *out = static_cast<uint8_t*>(::malloc(len64 ? len64 : 1));
  copy_out(r, r->hdr->head + 8, *out, len64);
  r->hdr->head += len64 + 8;
  pthread_cond_signal(&r->hdr->not_full);
  pthread_mutex_unlock(&r->hdr->mu);
  return static_cast<int64_t>(len64);
}

void shmring_free(uint8_t* p) { ::free(p); }

void shmring_close(void* handle) {  // mark EOF: readers drain then stop
  auto* r = static_cast<Ring*>(handle);
  pthread_mutex_lock(&r->hdr->mu);
  r->hdr->closed = 1;
  pthread_cond_broadcast(&r->hdr->not_empty);
  pthread_cond_broadcast(&r->hdr->not_full);
  pthread_mutex_unlock(&r->hdr->mu);
}

void shmring_detach(void* handle) {
  auto* r = static_cast<Ring*>(handle);
  bool owner = r->owner;
  std::string name = r->name;
  ::munmap(r->hdr, r->map_size);
  if (owner) ::shm_unlink(name.c_str());
  delete r;
}

}  // extern "C"
