"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities (see SURVEY.md for the blueprint; reference mounted at
/root/reference). The compute path is JAX/XLA/Pallas; the API surface
mirrors ``paddle``'s eager + distributed semantics.
"""
from __future__ import annotations

# Multi-process pods must join the global jax runtime BEFORE anything
# touches the XLA backend (see _bootstrap docstring).
from ._bootstrap import bootstrap as _mp_bootstrap

_mp_bootstrap()

# Core substrate first (flags/dtypes), then Tensor, then ops which register
# kernels, then method monkey-patching (reference-style late binding).
from .core import flags as _flags_mod
from .core.flags import get_flags, set_flags
from .core.dtype import (bfloat16, bool_, complex64, complex128, float16,
                         float32, float64, float8_e4m3fn, float8_e5m2,
                         get_default_dtype, iinfo, int8, int16, int32, int64,
                         finfo, set_default_dtype, uint8, uint16, uint32,
                         uint64, convert_dtype)
from .core.rng import seed, get_rng_state, set_rng_state
from .tensor import Parameter, Tensor, to_tensor
from .ops import *  # noqa: F401,F403 — creation/math/manipulation surface
from .ops import creation as _creation, manipulation as _manipulation, math as _math
from . import tensor_methods as _tensor_methods  # noqa: F401 (patches Tensor)
from .autograd import no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import amp  # noqa: F401
from . import vision  # noqa: F401
from . import jit  # noqa: F401
from . import device  # noqa: F401
from . import framework  # noqa: F401
from . import incubate  # noqa: F401
from .framework.io import load, save
from .framework.lazy_init import LazyGuard  # noqa: F401
from . import metric  # noqa: F401
from . import distributed  # noqa: F401
from . import hapi  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401
from . import static  # noqa: F401
from . import distribution  # noqa: F401
from . import quantization  # noqa: F401
from . import inference  # noqa: F401
from . import utils  # noqa: F401
import importlib as _importlib

# ops star-import binds ops.linalg onto the package under the name
# 'linalg', which would make `from . import linalg` short-circuit to
# the wrong module — import the top-level namespace module explicitly
linalg = _importlib.import_module(".linalg", __name__)
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import sparse  # noqa: F401
from . import geometric  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from . import regularizer  # noqa: F401
from . import sysconfig  # noqa: F401
from . import onnx  # noqa: F401
from . import hub  # noqa: F401
from .hapi import Model  # noqa: F401

# paddle-API aliases
bool = bool_  # noqa: A001

# bind the remaining reference Tensor methods now that the full
# function surface exists (reference: tensor/__init__.py method list)
_tensor_methods.patch_namespace_methods(globals())

__version__ = "0.1.0"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


_static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


def disable_static(place=None):
    global _static_mode
    _static_mode = False


def enable_static():
    """Enter the declare-then-run workflow. Unlike the reference, ops
    only record when they touch a ``static.data`` Variable — eager
    tensors keep working — so this just flips the mode reported by
    ``in_dynamic_mode`` (see paddle_tpu.static for the Program/Executor
    machinery)."""
    global _static_mode
    _static_mode = True
