"""Optimizers (reference: python/paddle/optimizer/*; fused kernels
phi/kernels/gpu/fused_adam_kernel.cu, adamw_kernel.cu, multi-tensor path
python/paddle/optimizer/adam.py:224-229).

TPU design: each optimizer's update rule is a pure function over the
pytree of (params, grads, states); ``step()`` runs ONE jitted multi-tensor
update for all parameters — the analog of the reference's FusedAdam — and
the whole thing inlines into a traced train step under jit.to_static.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import numpy as np
import jax.numpy as jnp

from ..autograd import no_grad
from ..nn.clip import ClipGradBase, ClipGradByGlobalNorm
from ..tensor import Parameter, Tensor
from . import lr as lr_sched
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "LarsMomentum", "Adam", "AdamW", "Adagrad",
           "Adadelta", "Adamax", "ASGD", "Rprop",
           "RMSProp", "Lamb", "lr"]

lr = lr_sched


class Optimizer:
    """Base optimizer with fused pytree updates."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip: Optional[ClipGradBase] = None, name=None,
                 multi_precision: bool = False, state_dtype=None):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            self._weight_decay = float(weight_decay)
            self._wd_mode = "l2"
        elif weight_decay is None:
            self._weight_decay = 0.0
            self._wd_mode = "l2"
        else:  # L1Decay/L2Decay-like object with a coeff (+ optional mode)
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
            self._wd_mode = getattr(weight_decay, "mode", "l2")
        self._multi_precision = multi_precision
        # dtype of per-param moment buffers. f32 default (the reference's
        # AdamW); bf16 halves optimizer-state HBM on memory-bound chips
        # (the update math still runs in f32 — states are cast in/out).
        self._state_dtype = (jnp.dtype(state_dtype) if state_dtype
                             else jnp.float32)
        self._states: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._step_count = 0
        self._jitted = None
        self._master_weights: Dict[int, jnp.ndarray] = {}

    def _decay_term(self, pf):
        """Weight-decay gradient term: wd*p for L2Decay, wd*sign(p) (the
        L1 subgradient) for L1Decay (reference: python/paddle/
        regularizer.py applied by the append_regularization_ops path)."""
        if self._wd_mode == "l1":
            return self._weight_decay * jnp.sign(pf)
        return self._weight_decay * pf

    # -- lr handling ---------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._lr = scheduler

    # -- state ---------------------------------------------------------
    def _param_state(self, p: Parameter, shapes: Dict[str, tuple]):
        st = self._states.get(id(p))
        if st is None:
            st = {k: jnp.zeros(s if s is not None else p._value.shape,
                               self._state_dtype)
                  for k, s in shapes.items()}
            if self._multi_precision and p._value.dtype != jnp.float32:
                self._master_weights[id(p)] = p._value.astype(jnp.float32)
            self._states[id(p)] = st
        return st

    def _state_shapes(self) -> Dict[str, tuple]:
        """Per-param state slots: name -> shape (None = same as param)."""
        return {}

    def _update_rule(self, p, g, state, lr_value, step):
        """Pure: returns (new_p, new_state_dict)."""
        raise NotImplementedError

    # -- the fused step -------------------------------------------------
    def _collect(self):
        params = [p for p in self._parameter_list
                  if p is not None and p.grad is not None and p.trainable]
        return params

    # -- sparse (SelectedRows) gradients --------------------------------
    def _sparse_update(self, p, pf, sr, state, lr_value, step):
        """Apply a merged SelectedRows grad. Default: densify (exact,
        same numerics as a dense grad); SGD/Adam override with row-wise
        scatter updates (reference: the optimizers'
        *DenseParamSparseGradKernel family)."""
        return self._update_rule(pf, sr.to_dense_value(), state,
                                 lr_value, step)

    def _apply_sparse(self, p, sr, lr_value, step_value, shapes):
        state = self._param_state(p, shapes)
        pf = self._master_weights.get(id(p), p._value)
        new_p, new_s = self._sparse_update(p, pf, sr,
                                           self._cast_state_in(state),
                                           lr_value, step_value)
        if id(p) in self._master_weights:
            self._master_weights[id(p)] = new_p
            p._value = new_p.astype(p._value.dtype)
        else:
            p._value = new_p
        self._states[id(p)] = self._cast_state_out(new_s)

    @no_grad()
    def step(self):
        from ..framework.selected_rows import (SelectedRows,
                                               merge_selected_rows)

        all_params = self._collect()
        if not all_params:
            return
        self._step_count += 1
        sparse = [p for p in all_params
                  if isinstance(p.grad, SelectedRows)]
        params = [p for p in all_params
                  if not isinstance(p.grad, SelectedRows)]
        extra_sq = None
        if sparse:
            shapes = self._state_shapes()
            lr_v = jnp.asarray(self.get_lr(), jnp.float32)
            st_v = jnp.asarray(self._step_count, jnp.int32)
            merged = [merge_selected_rows(p.grad) for p in sparse]
            if isinstance(self._grad_clip, ClipGradByGlobalNorm):
                # reference semantics (ClipGradByGlobalNorm): merged
                # SelectedRows grads join the global norm, and their
                # values scale by the same coefficient as the dense
                # grads (whose jitted clip sees the sparse sum via
                # extra_sq)
                sparse_sq = sum(
                    jnp.sum(jnp.square(sr.values.astype(jnp.float32)))
                    for sr in merged)
                dense_sq = sum(
                    jnp.sum(jnp.square(p.grad._value.astype(jnp.float32)))
                    for p in params)
                coef = self._grad_clip.coefficient(
                    jnp.sqrt(sparse_sq + dense_sq))
                from ..framework.selected_rows import SelectedRows as _SR

                merged = [_SR(sr.rows,
                              (sr.values * coef).astype(sr.values.dtype),
                              sr.height)
                          for sr in merged]
                extra_sq = sparse_sq
            for p, sr in zip(sparse, merged):
                self._apply_sparse(p, sr, lr_v, st_v, shapes)
        if not params:
            return
        shapes = self._state_shapes()
        states = [self._param_state(p, shapes) for p in params]
        pvals = [self._master_weights.get(id(p), p._value) for p in params]
        gvals = [p.grad._value for p in params]
        lr_value = jnp.asarray(self.get_lr(), jnp.float32)
        step_value = jnp.asarray(self._step_count, jnp.int32)

        new_pvals, new_states = self._fused_update(
            tuple(pvals), tuple(gvals), tuple(states), lr_value, step_value,
            extra_sq)

        for p, nv, ns in zip(params, new_pvals, new_states):
            if id(p) in self._master_weights:
                self._master_weights[id(p)] = nv
                p._value = nv.astype(p._value.dtype)
            else:
                p._value = nv
            self._states[id(p)] = ns

    def _cast_state_in(self, s):
        """Moment buffers may be stored low-precision (state_dtype); the
        update math always runs f32."""
        if self._state_dtype == jnp.float32:
            return s
        return {k: v.astype(jnp.float32)
                if jnp.issubdtype(v.dtype, jnp.floating) else v
                for k, v in s.items()}

    def _cast_state_out(self, s):
        if self._state_dtype == jnp.float32:
            return s
        return {k: v.astype(self._state_dtype)
                if jnp.issubdtype(v.dtype, jnp.floating) else v
                for k, v in s.items()}

    def _fused_update(self, pvals, gvals, states, lr_value, step_value,
                      extra_sq=None):
        # One jitted executable updating every parameter (multi-tensor
        # fused path — FusedAdam analog). jax.jit caches on pytree
        # structure + shapes. extra_sq: squared norm of the merged
        # sparse grads, folded into the global-norm clip so dense and
        # sparse sides scale by the same coefficient.
        if extra_sq is None:
            extra_sq = jnp.asarray(0.0, jnp.float32)

        def _clipped(gvals, extra_sq):
            clip = self._grad_clip
            if clip is None:
                return gvals
            if isinstance(clip, ClipGradByGlobalNorm):
                return clip.apply_values(list(gvals), extra_sq)[0]
            return clip.apply_values(list(gvals))[0]

        if self._jitted is None:

            def update_all(pvals, gvals, states, lr_value, step_value,
                           extra_sq):
                gvals = _clipped(gvals, extra_sq)
                out_p, out_s = [], []
                for p, g, s in zip(pvals, gvals, states):
                    np_, ns_ = self._update_rule(
                        p, g, self._cast_state_in(s), lr_value, step_value)
                    out_p.append(np_)
                    out_s.append(self._cast_state_out(ns_))
                return tuple(out_p), tuple(out_s)

            self._jitted = jax.jit(update_all)
        if any(isinstance(v, jax.core.Tracer) for v in pvals) or any(
                isinstance(v, jax.core.Tracer) for v in gvals):
            # already inside an enclosing trace (to_static train step)
            gvals = _clipped(gvals, extra_sq)
            out = [(lambda np_, ns_: (np_, self._cast_state_out(ns_)))(
                *self._update_rule(p, g, self._cast_state_in(s), lr_value,
                                   step_value))
                   for p, g, s in zip(pvals, gvals, states)]
            return tuple(o[0] for o in out), tuple(o[1] for o in out)
        return self._jitted(pvals, gvals, states, lr_value, step_value,
                            extra_sq)

    def clear_grad(self, set_to_zero: bool = False):
        if self._parameter_list:
            for p in self._parameter_list:
                if p is not None:
                    p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """(reference: python/paddle/optimizer/optimizer.py minimize).
        On a static Variable, records the train objective into its
        Program — Executor.run then performs backward + the fused step;
        on an eager Tensor, runs backward/step/clear now."""
        if getattr(loss, "_is_static_var", False):
            loss._program._train_objective = (loss, self)
            return None, None
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # -- checkpointing ---------------------------------------------------
    def state_dict(self) -> Dict:
        out = {"step_count": self._step_count}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                st = self._states.get(id(p))
                if st is not None:
                    key = p.name or f"param_{i}"
                    for k, v in st.items():
                        out[f"{key}.{k}"] = Tensor(v)
                    if id(p) in self._master_weights:
                        out[f"{key}.master_weight"] = Tensor(
                            self._master_weights[id(p)])
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state: Dict):
        self._step_count = int(state.get("step_count", 0))
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
        if self._parameter_list:
            shapes = self._state_shapes()
            for i, p in enumerate(self._parameter_list):
                key = p.name or f"param_{i}"
                st = {}
                for k in shapes:
                    sk = f"{key}.{k}"
                    if sk in state:
                        v = state[sk]
                        st[k] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                if st:
                    # preserve loaded master weights stored alongside
                    self._states[id(p)] = st
                mk = f"{key}.master_weight"
                if mk in state:
                    v = state[mk]
                    self._master_weights[id(p)] = (
                        v._value if isinstance(v, Tensor) else jnp.asarray(v)).astype(jnp.float32)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(p.astype(jnp.float32))
        return (p - (lr_value * g).astype(p.dtype)), state

    def _sparse_update(self, p, pf, sr, state, lr_value, step):
        """Row-wise SGD: touch only the looked-up rows (weight decay,
        when set, applies to those rows)."""
        rows = sr.rows
        g = sr.values.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(pf[rows].astype(jnp.float32))
        upd = (lr_value * g).astype(pf.dtype)
        return pf.at[rows].add(-upd), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _state_shapes(self):
        return {"velocity": None}

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(p.astype(jnp.float32))
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        return (p - (lr_value * upd).astype(p.dtype)), {"velocity": v}


class Adam(Optimizer):
    """(reference: python/paddle/optimizer/adam.py:38 → _C_ops.adam_ fused
    kernel at adam.py:331; here the fused kernel is the jitted pytree
    update in Optimizer._fused_update.)"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=True, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._decoupled = False
        self._lazy_mode = bool(lazy_mode)

    def _state_shapes(self):
        return {"moment1": None, "moment2": None}

    def _sparse_update(self, p, pf, sr, state, lr_value, step):
        """SelectedRows grad (reference: AdamDenseParamSparseGradKernel).
        lazy_mode=True updates moments/param ONLY at the touched rows
        (the reference's lazy path, exact for row-disjoint steps);
        lazy_mode=False keeps exact dense semantics by densifying."""
        if not self._lazy_mode:
            return super()._sparse_update(p, pf, sr, state, lr_value,
                                          step)
        rows = sr.rows
        g = sr.values.astype(jnp.float32)
        pf32 = pf.astype(jnp.float32)
        if self._weight_decay and not self._decoupled:
            g = g + self._decay_term(pf32[rows])
        m_r = self._beta1 * state["moment1"][rows] + (1 - self._beta1) * g
        v_r = self._beta2 * state["moment2"][rows] \
            + (1 - self._beta2) * jnp.square(g)
        t = step.astype(jnp.float32)
        mhat = m_r / (1 - self._beta1 ** t)
        vhat = v_r / (1 - self._beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
        if self._weight_decay and self._decoupled:
            upd = upd + self._decay_term(pf32[rows])
        new_p = pf.at[rows].add((-lr_value * upd).astype(pf.dtype))
        new_s = {"moment1": state["moment1"].at[rows].set(m_r),
                 "moment2": state["moment2"].at[rows].set(v_r)}
        return new_p, new_s

    def _update_rule(self, p, g, state, lr_value, step):
        pf = p.astype(jnp.float32)
        g = g.astype(jnp.float32)
        if self._weight_decay and not self._decoupled:
            g = g + self._decay_term(pf)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
        if self._weight_decay and self._decoupled:
            upd = upd + self._decay_term(pf)
        new_p = pf - lr_value * upd
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name=name)
        self._decoupled = True
        self._apply_decay_param_fun = apply_decay_param_fun
        # NOTE: apply_decay_param_fun is honored in step() by zeroing decay
        # for excluded params via per-param decay masks.
        self._decay_mask = None

    @no_grad()
    def step(self):
        if self._apply_decay_param_fun is not None and self._decay_mask is None:
            self._decay_mask = {
                id(p): bool(self._apply_decay_param_fun(p.name))
                for p in (self._parameter_list or [])}
        super().step()

    def _update_rule(self, p, g, state, lr_value, step):
        pf = p.astype(jnp.float32)
        g = g.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
        new_p = pf - lr_value * (upd + self._decay_term(pf))
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _state_shapes(self):
        return {"moment": None}

    def _param_state(self, p, shapes):
        st = self._states.get(id(p))
        if st is None:
            st = {"moment": jnp.full(p._value.shape, self._init_acc, jnp.float32)}
            self._states[id(p)] = st
        return st

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(p.astype(jnp.float32))
        acc = state["moment"] + jnp.square(g)
        new_p = p.astype(jnp.float32) - lr_value * g / (jnp.sqrt(acc) + self._epsilon)
        return new_p.astype(p.dtype), {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _state_shapes(self):
        return {"mean_square": None, "mean_grad": None, "momentum": None}

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(p.astype(jnp.float32))
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(g)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr_value * g / denom
        new_p = p.astype(jnp.float32) - mom
        return new_p.astype(p.dtype), {"mean_square": ms, "mean_grad": mg,
                                       "momentum": mom}


class Adadelta(Optimizer):
    """(reference: python/paddle/optimizer/adadelta.py over the phi
    adadelta kernel — accumulated-gradient/accumulated-update rule.)"""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _state_shapes(self):
        return {"avg_squared_grad": None, "avg_squared_update": None}

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(p.astype(jnp.float32))
        asg = self._rho * state["avg_squared_grad"] \
            + (1 - self._rho) * jnp.square(g)
        upd = g * jnp.sqrt(
            (state["avg_squared_update"] + self._epsilon)
            / (asg + self._epsilon))
        asu = self._rho * state["avg_squared_update"] \
            + (1 - self._rho) * jnp.square(upd)
        new_p = p.astype(jnp.float32) - lr_value * upd
        return new_p.astype(p.dtype), {"avg_squared_grad": asg,
                                       "avg_squared_update": asu}


class Adamax(Optimizer):
    """(reference: python/paddle/optimizer/adamax.py — infinity-norm
    Adam variant.)"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _state_shapes(self):
        return {"moment": None, "inf_norm": None}

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(p.astype(jnp.float32))
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        t = step.astype(jnp.float32)
        lr_t = lr_value / (1 - self._beta1 ** t)
        new_p = p.astype(jnp.float32) - lr_t * m / (u + self._epsilon)
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": u}


class ASGD(Optimizer):
    """(reference: python/paddle/optimizer/asgd.py over the phi asgd
    kernel — averaged SGD: keeps a running window-mean of the last N
    gradients; here the mean is the standard exponential form d/N.)"""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name, multi_precision)
        self._n = max(int(batch_num), 1)

    def _state_shapes(self):
        return {}  # shapes built directly in _param_state (hist is 3-D)

    def _param_state(self, p, shapes):
        st = self._states.get(id(p))
        if st is None:
            st = {"d": jnp.zeros(p._value.shape, jnp.float32),
                  "hist": jnp.zeros((self._n,) + tuple(p._value.shape),
                                    jnp.float32)}
            if self._multi_precision and p._value.dtype != jnp.float32:
                self._master_weights[id(p)] = p._value.astype(jnp.float32)
            self._states[id(p)] = st
        return st

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._decay_term(p.astype(jnp.float32))
        # d holds the sum of the last n gradients: rotate out the
        # oldest history slot, rotate in g (the reference's d/y buffers)
        idx = (step.astype(jnp.int32) - 1) % self._n
        oldest = state["hist"][idx]
        d = state["d"] - oldest + g
        hist = state["hist"].at[idx].set(g)
        new_p = p.astype(jnp.float32) - lr_value * d / self._n
        return new_p.astype(p.dtype), {"d": d, "hist": hist}


class Rprop(Optimizer):
    """(reference: python/paddle/optimizer/rprop.py — resilient
    backprop: per-weight step sizes grown/shrunk by gradient sign
    agreement; gradients' magnitudes are ignored.)"""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None, **kwargs):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name, multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _state_shapes(self):
        return {"prev_grad": None, "lr_w": None}

    def _param_state(self, p, shapes):
        st = self._states.get(id(p))
        if st is None:
            st = {"prev_grad": jnp.zeros(p._value.shape, jnp.float32),
                  "lr_w": jnp.full(p._value.shape, float(self.get_lr()),
                                   jnp.float32)}
            if self._multi_precision and p._value.dtype != jnp.float32:
                self._master_weights[id(p)] = p._value.astype(jnp.float32)
            self._states[id(p)] = st
        return st

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        sign = jnp.sign(g * state["prev_grad"])
        lr_w = jnp.clip(
            jnp.where(sign > 0, state["lr_w"] * self._eta_pos,
                      jnp.where(sign < 0, state["lr_w"] * self._eta_neg,
                                state["lr_w"])),
            self._lr_min, self._lr_max)
        # sign-disagreement steps are skipped (grad treated as 0)
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = p.astype(jnp.float32) - lr_w * jnp.sign(g_eff)
        return new_p.astype(p.dtype), {"prev_grad": g_eff, "lr_w": lr_w}


class Lamb(Optimizer):
    """(reference: python/paddle/optimizer/lamb.py + DistributedFusedLamb
    fusion kernels — layerwise-adaptive large-batch optimizer.)"""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _state_shapes(self):
        return {"moment1": None, "moment2": None}

    def _update_rule(self, p, g, state, lr_value, step):
        pf = p.astype(jnp.float32)
        g = g.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._decay_term(pf)
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = pf - lr_value * trust * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class LarsMomentum(Momentum):
    """LARS: layer-wise adaptive rate scaling over momentum
    (reference: fleet/meta_optimizers/lars_optimizer.py over the phi
    lars_momentum kernel — local_lr = lr * coeff * ||w|| /
    (||g|| + wd * ||w|| + eps), the large-batch training rule)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 epsilon=1e-8, exclude_from_weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None,
                 **kwargs):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=None, grad_clip=grad_clip,
                         multi_precision=multi_precision, name=name,
                         **kwargs)
        self._lars_coeff = float(lars_coeff)
        self._lars_wd = float(lars_weight_decay)
        self._eps = float(epsilon)
        self._exclude = list(exclude_from_weight_decay or [])

    def _param_state(self, p, shapes):
        st = super()._param_state(p, shapes)
        if "lars_skip" not in st:
            # per-param exclusion travels IN the state so the fused
            # positional update stays identity-free (name matching like
            # the reference's exclude_from_weight_decay)
            name = p.name or ""
            skip = any(tok in name for tok in self._exclude)
            st["lars_skip"] = jnp.float32(1.0 if skip else 0.0)
        return st

    def _update_rule(self, p, g, state, lr_value, step):
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        skip = state.get("lars_skip", jnp.float32(0.0)) > 0
        w_norm = jnp.sqrt(jnp.sum(pf * pf))
        g_norm = jnp.sqrt(jnp.sum(g * g))
        local = jnp.where(
            (~skip) & (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm
            / (g_norm + self._lars_wd * w_norm + self._eps),
            jnp.float32(1.0))
        g = g + jnp.where(skip, 0.0, self._lars_wd) * pf
        v = self._momentum * state["velocity"] + lr_value * local * g
        new_state = {"velocity": v}
        if "lars_skip" in state:
            new_state["lars_skip"] = state["lars_skip"]
        return (p - v.astype(p.dtype)), new_state
