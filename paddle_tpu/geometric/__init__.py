"""Graph-learning ops (paddle.geometric analog).

(reference: python/paddle/geometric/ — math.py segment ops over phi
segment_pool kernels, message_passing/send_recv.py graph_send_recv
CUDA kernels, reindex.py, sampling/neighbors.py. Here the gather/
scatter pairs lower to XLA scatter-add/min/max HLOs — TPU-native,
differentiable; data-dependent sampling/reindex run host-side by
design since their output shapes are data-dependent and cannot live
inside a traced XLA program.)
"""
from .math import (segment_max, segment_mean, segment_min,  # noqa: F401
                   segment_sum)
from .message_passing import send_u_recv, send_ue_recv, send_uv  # noqa: F401
from .reindex import reindex_graph  # noqa: F401
from .sampling import sample_neighbors  # noqa: F401

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max",
           "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
           "sample_neighbors"]
