"""Neighbor sampling (reference: python/paddle/geometric/sampling/
neighbors.py over the graph_sample_neighbors CUDA kernel). Sample
counts are data-dependent, so this runs host-side on numpy by design;
use the returned arrays with ``reindex_graph`` then feed the traced
GNN step.
"""
from __future__ import annotations

import numpy as np

import jax

from ..core.rng import get_key
from ..tensor import Tensor, to_tensor

__all__ = ["sample_neighbors"]


def _np(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Sample up to ``sample_size`` in-neighbors per input node from a
    CSC graph (row = concatenated neighbor ids, colptr = offsets).

    Returns (out_neighbors, out_count) and, with ``return_eids``, the
    sampled edge ids as a third output.
    """
    row_np = _np(row).astype(np.int64)
    colptr_np = _np(colptr).astype(np.int64)
    nodes = _np(input_nodes).astype(np.int64)
    eids_np = _np(eids).astype(np.int64) if eids is not None else None
    if return_eids and eids_np is None:
        raise ValueError("return_eids=True requires eids")

    seed = int(jax.random.randint(get_key(), (), 0, 2 ** 31 - 1))
    rng = np.random.RandomState(seed)
    out_n, out_c, out_e = [], [], []
    for v in nodes.tolist():
        lo, hi = int(colptr_np[v]), int(colptr_np[v + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(lo, hi)
        else:
            pick = lo + rng.choice(deg, size=sample_size, replace=False)
        out_n.append(row_np[pick])
        out_c.append(len(pick))
        if eids_np is not None:
            out_e.append(eids_np[pick])
    neighbors = (np.concatenate(out_n) if out_n
                 else np.zeros((0,), np.int64))
    count = np.asarray(out_c, np.int64)
    if return_eids:
        e = np.concatenate(out_e) if out_e else np.zeros((0,), np.int64)
        return to_tensor(neighbors), to_tensor(count), to_tensor(e)
    return to_tensor(neighbors), to_tensor(count)
