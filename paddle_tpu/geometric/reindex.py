"""Graph reindex (reference: python/paddle/geometric/reindex.py over the
graph_reindex CUDA hashmap kernel). Output shape is data-dependent
(unique node count), so this runs host-side on numpy by design — the
result feeds the traced GNN step as regular device arrays.
"""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor, to_tensor

__all__ = ["reindex_graph"]


def _np(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Reindex node ids to a dense [0, num_unique) range.

    Returns (reindex_src, reindex_dst, out_nodes): ``out_nodes`` is the
    input nodes followed by first-seen-order new neighbor ids;
    reindex_src/dst are the edge list expressed in the new ids.
    """
    x_np = _np(x).astype(np.int64)
    nbr = _np(neighbors).astype(np.int64)
    cnt = _np(count).astype(np.int64)
    if len(np.unique(x_np)) != len(x_np):
        # duplicates would desynchronize the positional dst ids from the
        # value-deduplicated node table (the reference requires unique
        # input nodes too — it just corrupts silently)
        raise ValueError("reindex_graph requires unique ids in x")
    if int(cnt.sum()) != len(nbr):
        raise ValueError(
            f"count.sum() ({int(cnt.sum())}) must equal len(neighbors) "
            f"({len(nbr)})")

    mapping = {}
    for v in x_np.tolist():
        mapping.setdefault(v, len(mapping))
    for v in nbr.tolist():
        mapping.setdefault(v, len(mapping))
    out_nodes = np.fromiter(mapping.keys(), np.int64, len(mapping))
    reindex_src = np.fromiter((mapping[v] for v in nbr.tolist()), np.int64,
                              len(nbr))
    reindex_dst = np.repeat(np.arange(len(x_np), dtype=np.int64), cnt)
    return (to_tensor(reindex_src), to_tensor(reindex_dst),
            to_tensor(out_nodes))
