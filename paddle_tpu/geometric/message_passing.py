"""Graph message passing (reference: python/paddle/geometric/
message_passing/send_recv.py over graph_send_recv / graph_send_ue_recv
CUDA kernels). gather(src) -> combine -> scatter(dst) as XLA HLOs.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import def_op
from ..core.enforce import enforce

__all__ = ["send_u_recv", "send_ue_recv", "send_uv"]

_MSG = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


def _scatter_reduce(msg, dst_index, reduce_op, out_rows):
    # delegate to the segment-reduction kernels (geometric/math.py) with
    # dst_index as the segment ids — one implementation of the
    # scatter-combine + unhit-row masking logic
    from .math import _minmax, _segment_mean_n, _segment_sum_n

    if reduce_op == "sum":
        return _segment_sum_n.raw(msg, dst_index, out_rows)
    if reduce_op == "mean":
        return _segment_mean_n.raw(msg, dst_index, out_rows)
    if reduce_op in ("min", "max"):
        return _minmax(msg, dst_index, out_rows, reduce_op)
    raise ValueError(f"unknown reduce_op {reduce_op!r}")


def _check_edges(src_index, dst_index):
    enforce(src_index.shape == dst_index.shape,
            lambda: "src_index and dst_index must have the same shape, got "
                    f"{src_index.shape} vs {dst_index.shape}")


@def_op("send_u_recv")
def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """out[d] = reduce over edges (s -> d) of x[s]."""
    _check_edges(src_index, dst_index)
    msg = jnp.take(x, src_index, axis=0)
    rows = int(out_size) if out_size is not None else x.shape[0]
    return _scatter_reduce(msg, dst_index, str(reduce_op), rows)


@def_op("send_ue_recv")
def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    """out[d] = reduce over edges e=(s -> d) of message_op(x[s], y[e])."""
    _check_edges(src_index, dst_index)
    msg = _MSG[str(message_op)](jnp.take(x, src_index, axis=0), y)
    rows = int(out_size) if out_size is not None else x.shape[0]
    return _scatter_reduce(msg, dst_index, str(reduce_op), rows)


@def_op("send_uv")
def send_uv(x, y, src_index, dst_index, message_op="add"):
    """Per-edge features: message_op(x[src], y[dst])."""
    _check_edges(src_index, dst_index)
    return _MSG[str(message_op)](jnp.take(x, src_index, axis=0),
                                 jnp.take(y, dst_index, axis=0))
