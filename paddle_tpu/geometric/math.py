"""Segment reductions (reference: python/paddle/geometric/math.py over
phi segment_pool kernels). Each lowers to one XLA scatter-combine HLO.
The output row count is data-dependent (``max(segment_ids)+1``), so it
is read on host before tracing and baked into the compiled program as a
static shape — the XLA contract for data-dependent output shapes.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import def_op
from ..core.enforce import enforce
from ..tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max"]


def _host_num_segments(segment_ids):
    ids = np.asarray(segment_ids._value if isinstance(segment_ids, Tensor)
                     else segment_ids)
    enforce(ids.ndim == 1,
            lambda: f"segment_ids must be 1-D, got rank {ids.ndim}")
    return int(ids.max()) + 1 if ids.size else 0


@def_op("segment_sum_n")
def _segment_sum_n(data, segment_ids, n):
    return jnp.zeros((int(n),) + data.shape[1:], data.dtype) \
        .at[segment_ids].add(data)


@def_op("segment_mean_n")
def _segment_mean_n(data, segment_ids, n):
    n = int(n)
    total = jnp.zeros((n,) + data.shape[1:], data.dtype) \
        .at[segment_ids].add(data)
    count = jnp.zeros((n,), data.dtype).at[segment_ids].add(1)
    return total / jnp.maximum(count.reshape((n,) + (1,) * (data.ndim - 1)),
                               1)


def _minmax(data, segment_ids, n, combine):
    n = int(n)
    fin = jnp.finfo(data.dtype) if jnp.issubdtype(
        data.dtype, jnp.floating) else jnp.iinfo(data.dtype)
    init = fin.max if combine == "min" else fin.min
    out = jnp.full((n,) + data.shape[1:], init, data.dtype)
    out = getattr(out.at[segment_ids], combine)(data)
    hit = jnp.zeros((n,), bool).at[segment_ids].set(True)
    return jnp.where(hit.reshape((n,) + (1,) * (data.ndim - 1)), out,
                     jnp.zeros_like(out))


@def_op("segment_min_n")
def _segment_min_n(data, segment_ids, n):
    return _minmax(data, segment_ids, n, "min")


@def_op("segment_max_n")
def _segment_max_n(data, segment_ids, n):
    return _minmax(data, segment_ids, n, "max")


def segment_sum(data, segment_ids, name=None):
    return _segment_sum_n(data, segment_ids,
                          _host_num_segments(segment_ids))


def segment_mean(data, segment_ids, name=None):
    return _segment_mean_n(data, segment_ids,
                           _host_num_segments(segment_ids))


def segment_min(data, segment_ids, name=None):
    return _segment_min_n(data, segment_ids,
                          _host_num_segments(segment_ids))


def segment_max(data, segment_ids, name=None):
    return _segment_max_n(data, segment_ids,
                          _host_num_segments(segment_ids))
