"""TCPStore: python surface over the native C++ store.

(reference: phi/core/distributed/store/tcp_store.h:121 TCPStore +
MasterDaemon; python/paddle/distributed/parallel.py:1099
create_or_get_global_tcp_store. The store bootstraps multi-host jobs
over DCN — coordinator address exchange, rank barriers — before any
ICI/XLA communication exists.)
"""
from __future__ import annotations

import ctypes
import os
import threading
import time
from typing import Optional

from ..core import native
from ..core.enforce import enforce

__all__ = ["TCPStore", "create_or_get_global_tcp_store"]

_global_store: Optional["TCPStore"] = None


class TCPStore:
    """KV store client (and, on the master rank, the server too)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 900.0):
        self._lib = native.load()
        enforce(self._lib is not None,
                "native library unavailable (csrc build failed)")
        # one socket per store object: serialize request/response pairs
        # (heartbeat + watcher threads share the connection)
        self._mu = threading.Lock()
        self._server = None
        self.timeout_ms = int(timeout * 1000)
        if is_master:
            bound = ctypes.c_int(0)
            # bind can transiently fail even on an OS-probed free port
            # (TOCTOU reuse / TIME_WAIT under loaded CI) — retry briefly
            for attempt in range(20):
                self._server = self._lib.tcpstore_server_start(
                    port, ctypes.byref(bound))
                if self._server:
                    break
                time.sleep(0.25)
            enforce(self._server, f"TCPStore: cannot bind port {port} "
                                  "(20 attempts)")
            port = bound.value
        self.host, self.port = host, port
        deadline = time.time() + timeout
        self._fd = -1
        while time.time() < deadline:
            self._fd = self._lib.tcpstore_connect(host.encode(), port)
            if self._fd >= 0:
                break
            time.sleep(0.05)
        enforce(self._fd >= 0,
                f"TCPStore: cannot connect to {host}:{port}")

    # Mirror of kMaxBlob in csrc/tcp_store.cpp. The server drops the
    # connection on an oversized frame, which would surface to peers as an
    # opaque timeout — so fail fast on the client with a clear message.
    MAX_BLOB = 64 * 1024 * 1024

    def set(self, key: str, value) -> None:
        from . import failpoints as _fp

        data = value if isinstance(value, (bytes, bytearray)) else \
            str(value).encode()
        # fault-injection site: a hung/raising store is how a control-
        # plane outage presents to heartbeats and barriers
        data = _fp.hit("store.set", bytes(data))
        if len(data) > self.MAX_BLOB:
            raise ValueError(
                f"TCPStore.set({key!r}): payload of {len(data)} bytes "
                f"exceeds the {self.MAX_BLOB}-byte frame cap; the store "
                "carries bootstrap metadata, not tensor data — shard or "
                "compress large objects before shipping them")
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        with self._mu:
            rc = self._lib.tcpstore_set(self._fd, key.encode(), buf,
                                        len(data))
        enforce(rc == 0, f"TCPStore.set({key!r}) failed")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        from . import failpoints as _fp

        _fp.hit("store.get")
        out = ctypes.POINTER(ctypes.c_uint8)()
        ms = int(timeout * 1000) if timeout is not None else self.timeout_ms
        with self._mu:
            n = self._lib.tcpstore_get(self._fd, key.encode(),
                                       ms, ctypes.byref(out))
            enforce(n >= 0, f"TCPStore.get({key!r}) timed out")
            data = ctypes.string_at(out, n)
            self._lib.tcpstore_free(out)
        return data

    def add(self, key: str, delta: int) -> int:
        with self._mu:
            v = self._lib.tcpstore_add(self._fd, key.encode(), int(delta))
        enforce(v != -(2 ** 63), f"TCPStore.add({key!r}) failed")
        return int(v)

    def wait(self, key: str, timeout: Optional[float] = None) -> None:
        ms = int(timeout * 1000) if timeout else self.timeout_ms
        with self._mu:
            rc = self._lib.tcpstore_wait(self._fd, key.encode(), ms)
        enforce(rc == 0, f"TCPStore.wait({key!r}) timed out")

    def check(self, key: str) -> bool:
        with self._mu:
            return self._lib.tcpstore_check(self._fd, key.encode()) == 1

    def delete_key(self, key: str) -> None:
        with self._mu:
            self._lib.tcpstore_delete(self._fd, key.encode())

    def barrier(self, name: str, world_size: int,
                timeout: Optional[float] = None) -> None:
        """Reusable count-up barrier via the atomic ADD counter.

        The go-key is namespaced by generation (arrival count //
        world_size), so the same barrier name can be reused across steps
        and across elastic restarts without tripping on a stale go-key
        left in the store by a previous generation.
        """
        n = self.add(f"__barrier__/{name}", 1)
        gen = (n - 1) // world_size
        go = f"__barrier__/{name}/go/{gen}"
        if n == (gen + 1) * world_size:
            self.set(go, b"1")
            # Reap the previous generation's go-key so long jobs don't
            # accumulate one store entry per barrier call. gen-1 is safe
            # to delete: every rank must have passed it to arrive here.
            if gen > 0:
                self.delete_key(f"__barrier__/{name}/go/{gen - 1}")
        self.wait(go, timeout)

    def close(self) -> None:
        if self._fd >= 0:
            self._lib.tcpstore_close(self._fd)
            self._fd = -1
        if self._server:
            self._lib.tcpstore_server_stop(self._server)
            self._server = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def create_or_get_global_tcp_store() -> TCPStore:
    """(reference parallel.py:1099) — master/port from the launcher envs
    PADDLE_MASTER / PADDLE_TRAINER_ID."""
    global _global_store
    if _global_store is None:
        master = os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
        host, _, port = master.partition(":")
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        _global_store = TCPStore(host or "127.0.0.1", int(port or 0),
                                 is_master=(rank == 0), world_size=world)
    return _global_store
