"""Multi-process runtime bootstrap + host-side object collectives.

This is the process-real half of the distributed stack (reference:
python/paddle/distributed/parallel.py:943-1101 — TCPStore rendezvous →
ProcessGroup creation; the Gloo host collectives the reference keeps for
object all_gather / barrier). TPU-native layering:

- device collectives  → XLA collectives over ICI inside shard_map
  (collective.py), which need every process to join one jax runtime:
  that is ``jax.distributed.initialize``, bootstrapped here over the
  native TCPStore (csrc/tcp_store.cpp).
- host collectives    → pickled blobs through the same TCPStore over
  DCN (the Gloo role: all_gather_object, broadcast_object_list,
  barrier) — no device traffic, works before any mesh exists.

One process per host drives all local chips (XLA single-controller);
``launch --nproc_per_node K`` forks K ranked processes for CPU
simulation, exactly the reference's multi-process test harness
(SURVEY.md §4: _run_cluster_gloo / fake device strategy).
"""
from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional

__all__ = [
    "ensure_initialized", "is_multiprocess", "process_rank",
    "process_world", "host_barrier", "all_gather_object_host",
    "gather_object_host",
    "broadcast_object_host", "send_object", "recv_object",
]

_initialized = False
_gen = 0  # monotonically-increasing collective-call counter


def process_world() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def process_rank() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def is_multiprocess() -> bool:
    return process_world() > 1


def _store():
    from .store import create_or_get_global_tcp_store

    return create_or_get_global_tcp_store()


def ensure_initialized() -> None:
    """Join the global jax runtime (idempotent).

    The actual ``jax.distributed.initialize`` runs in
    ``paddle_tpu._bootstrap`` at import time — it must precede any XLA
    backend touch. This re-invocation covers direct users of the
    distributed API in embeddings where the package import order differs.
    After it, ``jax.devices()`` is the GLOBAL device list and in-graph
    collectives cross process boundaries (gloo on CPU, ICI/DCN on TPU).
    """
    global _initialized
    if _initialized:
        return
    from .._bootstrap import bootstrap

    bootstrap()
    _initialized = True


# ---------------------------------------------------------------------------
# Host-side object collectives (the Gloo role). All ranks must call each
# collective the same number of times in the same order — the shared
# generation counter keys each call's store namespace so values never
# collide across calls or restarts.
# ---------------------------------------------------------------------------


def _next_gen() -> int:
    global _gen
    _gen += 1
    return _gen


def host_barrier(name: str = "host", timeout: Optional[float] = None) -> None:
    if not is_multiprocess():
        return
    # Fixed (reusable) barrier name: store.barrier generation-keys each
    # pass internally and reaps the previous generation's go-key, so the
    # coordinator's footprint stays O(#distinct names), not O(#calls).
    _next_gen()
    _store().barrier(f"hb/{name}", process_world(), timeout)


def all_gather_object_host(obj: Any,
                           timeout: Optional[float] = None) -> List[Any]:
    """Gather one picklable object from every process, ordered by rank."""
    if not is_multiprocess():
        return [obj]
    store, gen = _store(), _next_gen()
    rank, world = process_rank(), process_world()
    store.set(f"og/{gen}/{rank}", pickle.dumps(obj, protocol=4))
    out = [pickle.loads(store.get(f"og/{gen}/{r}", timeout))
           for r in range(world)]
    # clean own key next round: barrier then delete own slot (fixed
    # reusable barrier name — see host_barrier)
    store.barrier("og", world, timeout)
    store.delete_key(f"og/{gen}/{rank}")
    return out


def gather_object_host(obj: Any, dst: int = 0,
                       timeout: Optional[float] = None):
    """Gather one picklable object from every process ON ``dst`` only
    (others return None) — O(world x obj) at the root, O(obj)
    elsewhere, unlike all_gather."""
    if not is_multiprocess():
        return [obj]
    store, gen = _store(), _next_gen()
    rank, world = process_rank(), process_world()
    store.set(f"go/{gen}/{rank}", pickle.dumps(obj, protocol=4))
    out = None
    if rank == dst:
        out = [pickle.loads(store.get(f"go/{gen}/{r}", timeout))
               for r in range(world)]
    store.barrier("go", world, timeout)
    store.delete_key(f"go/{gen}/{rank}")
    return out


def broadcast_object_host(obj: Any, src: int = 0,
                          timeout: Optional[float] = None) -> Any:
    if not is_multiprocess():
        return obj
    store, gen = _store(), _next_gen()
    if process_rank() == src:
        store.set(f"bc/{gen}", pickle.dumps(obj, protocol=4))
        out = obj
    else:
        out = pickle.loads(store.get(f"bc/{gen}", timeout))
    store.barrier("bc", process_world(), timeout)
    if process_rank() == src:
        store.delete_key(f"bc/{gen}")
    return out


def send_object(obj: Any, dst: int) -> None:
    """Host-side point-to-point (the reference's eager send over gloo).

    Pairs with :func:`recv_object` on ``dst``. Per-(src,dst) sequence
    numbers keep repeated sends ordered without a global generation.
    """
    store = _store()
    src = process_rank()
    seq = store.add(f"p2p/{src}->{dst}/seq", 1)
    store.set(f"p2p/{src}->{dst}/{seq}", pickle.dumps(obj, protocol=4))


def recv_object(src: int, timeout: Optional[float] = None) -> Any:
    store = _store()
    dst = process_rank()
    seq = store.add(f"p2p/{src}->{dst}/rseq", 1)
    data = store.get(f"p2p/{src}->{dst}/{seq}", timeout)
    store.delete_key(f"p2p/{src}->{dst}/{seq}")
    return pickle.loads(data)
