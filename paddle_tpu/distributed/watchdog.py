"""Hang detection for compiled collective steps.

(reference: phi/core/distributed/comm_task_manager.h:37 CommTaskManager —
background threads tracking in-flight NCCL collectives,
NCCLCommTask::IsTimeout/AbortComm, ErrorHandlingMode::TearDown;
enabled via FLAGS_enable_async_trace.)

TPU-native: XLA collectives are compiled into the step, not enqueued as
tasks, so hang detection wraps the *step execution*: a monitor thread
arms a deadline around each tracked region (dispatch → block_until_ready)
and fires the timeout handler if the device never comes back — the
typical cause being a peer host dropping out of a multi-host collective.

``error_handling`` modes on timeout (a flight record is dumped first in
every mode):

- ``"raise"``  — record the timeout; ``check()`` (called when a tracked
  region exits, and between steps) raises :class:`TimeoutError_`.
- ``"log"``    — log an ERROR naming the hung region and the flight-
  record path, and keep going (observe-only deployments).
- ``"teardown"`` — ``os.abort()`` so the launcher's watcher restarts
  the pod (the reference's ErrorHandlingMode::TearDown).

Lifecycle: the monitor thread starts lazily on the first tracked
region and is joined by ``shutdown()``; the manager (and the ``watch``
wrapper) are context managers so tests and loops can scope them —
``with CommTaskManager(...) as mgr: ...`` / ``with watch(step) as w:``
never leak a monitor thread.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

__all__ = ["CommTaskManager", "TimeoutError_", "watch"]

logger = logging.getLogger("paddle_tpu.watchdog")


class TimeoutError_(RuntimeError):
    pass


class _Task:
    def __init__(self, name: str, deadline: float):
        self.name = name
        self.deadline = deadline
        self.done = False


class CommTaskManager:
    """Tracks in-flight step executions against a timeout."""

    def __init__(self, timeout: float = 1800.0,
                 error_handling: str = "raise",
                 on_timeout: Optional[Callable] = None,
                 poll_interval: float = 0.5):
        if error_handling not in ("raise", "log", "teardown"):
            raise ValueError(
                f"error_handling {error_handling!r}: choose "
                "raise | log | teardown")
        self.timeout = timeout
        self.error_handling = error_handling
        self.on_timeout = on_timeout
        self.poll = poll_interval
        self.last_flight_record: Optional[str] = None
        self._tasks = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._timed_out: Optional[str] = None
        # lazy: no monitor thread until something is actually tracked
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="watchdog-monitor")
                self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.poll):
            now = time.monotonic()
            with self._lock:
                hung = [t for t in self._tasks
                        if not t.done and now > t.deadline]
                self._tasks = [t for t in self._tasks if not t.done]
            for t in hung:
                t.done = True
                self._dump_flight_record(t.name)
                # published AFTER the dump so a concurrent check()
                # never raises with the flight record still unwritten
                with self._lock:
                    self._timed_out = t.name
                if self.on_timeout:
                    self.on_timeout(t.name)
                if self.error_handling == "log":
                    logger.error(
                        "watchdog: tracked region '%s' exceeded %.1fs "
                        "without the device coming back (peer likely "
                        "left the mesh); flight record: %s", t.name,
                        self.timeout, self.last_flight_record or "<none>")
                elif self.error_handling == "teardown":
                    os.abort()

    def _dump_flight_record(self, name: str):
        """Before raising/logging/tearing down, persist the stall
        flight-record (last-N metric snapshots + in-flight named regions
        + every thread's stack) — the post-mortem the reference dumps
        from its async-trace task queue (FLAGS_enable_async_trace)."""
        try:
            from ..observability import flight as _flight

            path = _flight.dump(
                reason=f"watchdog: '{name}' exceeded {self.timeout}s "
                       "without the device coming back")
        except Exception:       # the dump must never mask the timeout
            path = None
        with self._lock:
            self.last_flight_record = path

    def check(self):
        """Raise if any tracked region has timed out (call between
        steps — the main thread may be past the hung region by then)."""
        if self.error_handling != "raise":
            return
        with self._lock:
            name, self._timed_out = self._timed_out, None
            record = self.last_flight_record
        if name is not None:
            where = f"; flight record: {record}" if record else ""
            raise TimeoutError_(
                f"collective step '{name}' exceeded "
                f"{self.timeout}s — a peer likely left the mesh "
                f"(reference: NCCLCommTask::IsTimeout){where}")

    def track(self, name: str = "step", timeout: Optional[float] = None):
        self._ensure_thread()
        return _Tracker(self, name, timeout or self.timeout)

    def shutdown(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def __enter__(self) -> "CommTaskManager":
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


class _Tracker:
    def __init__(self, mgr: CommTaskManager, name: str, timeout: float):
        self._mgr = mgr
        self._name = name
        self._timeout = timeout
        self._task = None

    def __enter__(self):
        self._task = _Task(self._name,
                           time.monotonic() + self._timeout)
        # the tracked region shows up in stall flight-records as an
        # in-flight named region on this thread
        from ..observability import trace as _trace

        self._region = _trace.annotate(f"watchdog:{self._name}")
        self._region.__enter__()
        with self._mgr._lock:
            self._mgr._tasks.append(self._task)
        return self

    def __exit__(self, *exc):
        self._task.done = True
        self._region.__exit__(None, None, None)
        self._mgr.check()
        return False


class _Watched:
    """Callable wrapper around a step fn + its watchdog; context-manager
    and ``shutdown()`` wiring so the monitor thread never leaks."""

    def __init__(self, fn: Callable, mgr: CommTaskManager, name: str):
        self._fn = fn
        self._name = name
        self._watchdog = mgr

    def __call__(self, *args, **kwargs):
        import jax

        with self._watchdog.track(self._name):
            out = self._fn(*args, **kwargs)
            jax.block_until_ready(
                jax.tree_util.tree_map(
                    lambda t: getattr(t, "_value", t), out))
        return out

    def shutdown(self):
        self._watchdog.shutdown()

    def __enter__(self) -> "_Watched":
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


def watch(fn: Callable, timeout: float = 1800.0, name: str = "step",
          **mgr_kw) -> _Watched:
    """Wrap a compiled step so each call is tracked: blocks until the
    result is device-ready inside the watched region.

    The wrapper owns its CommTaskManager — scope it (``with watch(step)
    as w: ...``) or call ``w.shutdown()`` when done; the monitor thread
    only starts on the first call.
    """
    return _Watched(fn, CommTaskManager(timeout=timeout, **mgr_kw), name)
