"""Hang detection for compiled collective steps.

(reference: phi/core/distributed/comm_task_manager.h:37 CommTaskManager —
background threads tracking in-flight NCCL collectives,
NCCLCommTask::IsTimeout/AbortComm, ErrorHandlingMode::TearDown;
enabled via FLAGS_enable_async_trace.)

TPU-native: XLA collectives are compiled into the step, not enqueued as
tasks, so hang detection wraps the *step execution*: a monitor thread
arms a deadline around each tracked region (dispatch → block_until_ready)
and fires the timeout handler if the device never comes back — the
typical cause being a peer host dropping out of a multi-host collective.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

__all__ = ["CommTaskManager", "TimeoutError_", "watch"]


class TimeoutError_(RuntimeError):
    pass


class _Task:
    def __init__(self, name: str, deadline: float):
        self.name = name
        self.deadline = deadline
        self.done = False


class CommTaskManager:
    """Tracks in-flight step executions against a timeout.

    ``error_handling``: "raise" (raise TimeoutError_ in the monitor and
    record it for the main thread), "log", or "teardown" (SIGABRT the
    process — the reference's ErrorHandlingMode::TearDown, letting the
    launcher's watcher restart the pod).
    """

    def __init__(self, timeout: float = 1800.0,
                 error_handling: str = "raise",
                 on_timeout: Optional[Callable] = None,
                 poll_interval: float = 0.5):
        self.timeout = timeout
        self.error_handling = error_handling
        self.on_timeout = on_timeout
        self.poll = poll_interval
        self.last_flight_record: Optional[str] = None
        self._tasks = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._timed_out: Optional[str] = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="watchdog-monitor")
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.poll):
            now = time.monotonic()
            with self._lock:
                hung = [t for t in self._tasks
                        if not t.done and now > t.deadline]
                self._tasks = [t for t in self._tasks if not t.done]
            for t in hung:
                t.done = True
                self._timed_out = t.name
                self._dump_flight_record(t.name)
                if self.on_timeout:
                    self.on_timeout(t.name)
                if self.error_handling == "teardown":
                    os.abort()

    def _dump_flight_record(self, name: str):
        """Before raising/tearing down, persist the stall flight-record
        (last-N metric snapshots + in-flight named regions + every
        thread's stack) — the post-mortem the reference dumps from its
        async-trace task queue (FLAGS_enable_async_trace)."""
        try:
            from ..observability import flight as _flight

            self.last_flight_record = _flight.dump(
                reason=f"watchdog: '{name}' exceeded {self.timeout}s "
                       "without the device coming back")
        except Exception:       # the dump must never mask the timeout
            self.last_flight_record = None

    def check(self):
        """Raise if any tracked region has timed out (call between
        steps — the main thread may be past the hung region by then)."""
        if self._timed_out is not None and self.error_handling == "raise":
            name, self._timed_out = self._timed_out, None
            where = (f"; flight record: {self.last_flight_record}"
                     if self.last_flight_record else "")
            raise TimeoutError_(
                f"collective step '{name}' exceeded "
                f"{self.timeout}s — a peer likely left the mesh "
                f"(reference: NCCLCommTask::IsTimeout){where}")

    def track(self, name: str = "step", timeout: Optional[float] = None):
        return _Tracker(self, name, timeout or self.timeout)

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=2)


class _Tracker:
    def __init__(self, mgr: CommTaskManager, name: str, timeout: float):
        self._mgr = mgr
        self._name = name
        self._timeout = timeout
        self._task = None

    def __enter__(self):
        self._task = _Task(self._name,
                           time.monotonic() + self._timeout)
        # the tracked region shows up in stall flight-records as an
        # in-flight named region on this thread
        from ..observability import trace as _trace

        self._region = _trace.annotate(f"watchdog:{self._name}")
        self._region.__enter__()
        with self._mgr._lock:
            self._mgr._tasks.append(self._task)
        return self

    def __exit__(self, *exc):
        self._task.done = True
        self._region.__exit__(None, None, None)
        self._mgr.check()
        return False


def watch(fn: Callable, timeout: float = 1800.0, name: str = "step",
          **mgr_kw):
    """Wrap a compiled step so each call is tracked: blocks until the
    result is device-ready inside the watched region."""
    mgr = CommTaskManager(timeout=timeout, **mgr_kw)

    def wrapped(*args, **kwargs):
        import jax

        with mgr.track(name):
            out = fn(*args, **kwargs)
            jax.block_until_ready(
                jax.tree_util.tree_map(
                    lambda t: getattr(t, "_value", t), out))
        return out

    wrapped._watchdog = mgr
    return wrapped
