"""Group-sharded (ZeRO) data parallelism — public API.

TPU-native re-design of the reference's group_sharded_parallel
(reference: python/paddle/distributed/sharding/group_sharded.py:40;
stage impls meta_parallel/sharding/group_sharded_stage2.py,
group_sharded_stage3.py, group_sharded_optimizer_stage2.py).

Levels (reference naming):
- ``os``      — ZeRO-1: optimizer states sharded over the 'sharding' axis.
- ``os_g``    — ZeRO-2: + gradients reduce-scattered to the owner shard.
- ``p_g_os``  — ZeRO-3: + parameters stored sharded, all-gathered per step.

Mechanically all three are declarative here: parameters/states carry a
sharding plan (engine._ZeroPlan) and the compiled SPMD step emits
all_gather / psum_scatter on ICI with donated buffers — XLA's scheduler
provides the comm/compute overlap the reference hand-codes with comm
streams (group_sharded_stage2.py:_comm_grads).
"""
from __future__ import annotations

from typing import Optional

from ..fleet.meta_optimizers.dygraph_optimizer import DygraphShardingOptimizer

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group=None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm: bool = False,
                           dp_group=None, exclude_layer=None):
    """Wrap model/optimizer for ZeRO training (reference group_sharded.py:40).

    Returns ``(model, optimizer, scaler)``. The returned objects are the
    same instances, annotated with the sharding plan the ParallelEngine
    honors when compiling the train step over a mesh with a 'sharding'
    axis (strategy.hybrid_configs["sharding_degree"] > 1).
    """
    levels = ("os", "os_g", "p_g_os")
    if level not in levels:
        raise ValueError(f"level must be one of {levels}, got {level!r}")
    inner = getattr(optimizer, "_inner_opt", optimizer)
    inner.state_partition_axis = "sharding"
    if level in ("os_g", "p_g_os"):
        inner.shard_gradients = True  # informational; engine scatters anyway
    if level == "p_g_os":
        for p in model.parameters():
            if p.trainable:
                p._zero3 = True
        model._group_sharded_stage = 3
    else:
        model._group_sharded_stage = 2 if level == "os_g" else 1
    if not isinstance(optimizer, DygraphShardingOptimizer) and \
            not hasattr(optimizer, "_inner_opt"):
        optimizer = DygraphShardingOptimizer(optimizer)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Save a group-sharded model's full (unsharded) state
    (reference group_sharded.py:149). Parameters are global jax.Arrays,
    so the gather is implicit in ``.numpy()``."""
    import os

    from ...framework import io as _io

    os.makedirs(output, exist_ok=True)
    _io.save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        _io.save(optimizer.state_dict(),
                 os.path.join(output, "model.pdopt"))
