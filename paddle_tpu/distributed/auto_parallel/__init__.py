from .api import (Partial, Placement, ProcessMesh, Replicate, Shard,
                  dtensor_from_fn, reshard, shard_layer,
                  shard_tensor)  # noqa: F401
from .engine import Engine  # noqa: F401

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
           "Engine"]
