"""Semi-auto parallel (DistTensor) API.

(reference: python/paddle/distributed/auto_parallel/api.py —
shard_tensor:126, dtensor_from_fn:342, reshard:441, shard_layer; C++
DistTensor phi/core/distributed/auto_parallel/dist_tensor.h with
ProcessMesh/TensorDistAttr dist_attr.h; pairwise reshard functions
phi/core/distributed/auto_parallel/reshard/*.cc.)

TPU-native: a "DistTensor" IS a global ``jax.Array`` with a
``NamedSharding`` — placements map 1:1 onto PartitionSpec entries, and
the reference's whole pairwise reshard engine (r↔s, s↔r, p↔r, s↔s,
nd-mesh) collapses into ``jax.device_put(x, new_sharding)``: XLA/IFRT
computes the minimal resharding collectives. ``Partial`` placements are
realized immediately (psum on placement) since jax.Arrays don't carry
pending-reduction state.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...core.enforce import enforce
from ...nn.layer import Layer
from ...tensor import Tensor

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Shard the tensor's ``dim`` over the corresponding mesh dim
    (reference dist_attr Shard placement)."""

    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    """Pending-reduction placement. jax.Arrays carry no partial state, so
    applying it sums the operand over the mesh dim on placement."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """N-d logical process mesh (reference: ProcessMesh in
    distributed/auto_parallel/process_mesh.py; C++ process_mesh.h)."""

    def __init__(self, mesh, dim_names: Optional[List[str]] = None,
                 shape=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        enforce(arr.ndim == len(dim_names),
                f"mesh ndim {arr.ndim} != len(dim_names) {len(dim_names)}")
        self._ids = arr
        self._dim_names = list(dim_names)
        devs = jax.devices()
        enforce(int(arr.max()) < len(devs),
                f"mesh references device {int(arr.max())} but only "
                f"{len(devs)} devices are visible")
        dev_arr = np.empty(arr.shape, dtype=object)
        for idx in np.ndindex(arr.shape):
            dev_arr[idx] = devs[int(arr[idx])]
        self.jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return [int(i) for i in self._ids.flatten()]

    def get_dim_size(self, name: str) -> int:
        return self._ids.shape[self._dim_names.index(name)]

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self._dim_names})")


def _placements_to_spec(mesh: ProcessMesh, placements) -> P:
    """placements[i] describes mesh dim i → PartitionSpec over tensor dims."""
    ndim_t = max([p.dim for p in placements if isinstance(p, Shard)],
                 default=-1) + 1
    parts: List = [None] * ndim_t
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            name = mesh.dim_names[mesh_dim]
            cur = parts[pl.dim]
            if cur is None:
                parts[pl.dim] = name
            elif isinstance(cur, tuple):
                parts[pl.dim] = cur + (name,)
            else:
                parts[pl.dim] = (cur, name)
    return P(*parts)


def shard_tensor(data, mesh: ProcessMesh, placements,
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Create a distributed tensor placed per ``placements``
    (reference api.py:126). The result is a normal Tensor whose backing
    jax.Array is globally sharded; ``dist_attr`` records the spec."""
    t = data if isinstance(data, Tensor) else Tensor(
        jax.numpy.asarray(data))
    placements = list(placements)
    enforce(len(placements) == mesh.ndim,
            f"need one placement per mesh dim ({mesh.ndim}), got "
            f"{len(placements)}")
    enforce(not any(p.is_partial() for p in placements),
            "Partial placement is only produced by computations; "
            "shard_tensor accepts Shard/Replicate")
    spec = _placements_to_spec(mesh, placements)
    sharding = NamedSharding(mesh.jax_mesh, spec)
    val = jax.device_put(t._value, sharding)
    out = Tensor(val, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient)
    out.dist_attr = spec
    out.process_mesh = mesh
    out.placements = placements
    return out


def dtensor_from_fn(fn: Callable, mesh: ProcessMesh, placements,
                    *args, **kwargs) -> Tensor:
    """Build then shard (reference api.py:342 — e.g.
    dtensor_from_fn(paddle.ones, mesh, [Shard(0)], shape))."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """Move a dist tensor to a new mesh/placement layout
    (reference api.py:441; C++ reshard/*_reshard_function.cc). XLA/IFRT
    emits the minimal collective for the transition."""
    return shard_tensor(x, mesh, placements)


def shard_layer(layer: Layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None) -> Layer:
    """Shard a layer's parameters across a mesh (reference api.py
    shard_layer). ``shard_fn(name, layer, mesh)`` customizes per-layer
    placement; default replicates every parameter on the mesh."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for p in sublayer.parameters(include_sublayers=False):
                v = shard_tensor(p, mesh,
                                 [Replicate()] * mesh.ndim)
                p._value = v._value
                p.dist_attr = v.dist_attr

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer
