"""Per-op SPMD (sharding) propagation rules.

(reference: paddle/phi/infermeta/spmd_rules/*.cc — matmul.cc,
elementwise.cc, reduction.cc, embedding.cc, reshape.cc, transpose.cc,
softmax.cc... — there each PHI op infers its outputs' TensorDistAttr
from the inputs' during static planning.)

TPU-native split of responsibilities: the HEAVY half of sharding
propagation (choosing collectives, partial-sum placement, resharding)
is owned by XLA's GSPMD when the auto-parallel Engine jit-compiles the
step — these rules only propagate the EAGER metadata (`Tensor.dist_attr`
PartitionSpecs) through the dispatch chokepoint so user code can ask
"how is this result distributed?" between ops, exactly like the
reference's eager DistTensor does.

Rules receive normalized input specs (tuples padded to each input's
rank) and return one spec tuple per output, or None when the rule
cannot say (the output is then left unannotated rather than wrongly
annotated).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

_RULES: Dict[str, Callable] = {}


def register_rule(*names):
    def deco(fn):
        for n in names:
            _RULES[n] = fn
        return fn
    return deco


def _spec_of(t) -> Optional[Tuple]:
    da = getattr(t, "dist_attr", None)
    if da is None:
        return None
    parts = tuple(da) if isinstance(da, P) else tuple(da)
    nd = getattr(t._value, "ndim", len(parts))
    return parts + (None,) * (nd - len(parts))


def _merge_entry(a, b):
    if a == b:
        return a
    if a is None:
        return b
    if b is None:
        return a
    return None  # conflicting shardings: give up on this dim


# ---------------------------------------------------------------------------
# rule implementations
# ---------------------------------------------------------------------------


def _elementwise(op, in_ts, out_vals, args, kwargs):
    """Broadcast elementwise: align specs right, merge per dim
    (reference elementwise.cc)."""
    out = out_vals[0]
    nd = out.ndim
    parts: List = [None] * nd
    for t in in_ts:
        s = _spec_of(t)
        if s is None:
            continue
        tnd = t._value.ndim
        for i, e in enumerate(s):
            # right-aligned broadcast: dim i of t maps to out dim
            oi = i + (nd - tnd)
            if t._value.shape[i] == out.shape[oi]:
                parts[oi] = _merge_entry(parts[oi], e)
    return [tuple(parts)]


def _passthrough_same_shape(op, in_ts, out_vals, args, kwargs):
    """Unary (or first-input-dominant) shape-preserving ops."""
    for t in in_ts:
        s = _spec_of(t)
        if s is not None and tuple(t._value.shape) == tuple(
                out_vals[0].shape):
            return [s]
    return None


@register_rule("matmul")
def _matmul(op, in_ts, out_vals, args, kwargs):
    """(reference matmul.cc) batch/m dims from x, n from y; the
    contracted dim's sharding is dropped (GSPMD realizes the partial
    sum; metadata-wise the output is unsharded there)."""
    x, y = in_ts[0], in_ts[1]
    sx, sy = _spec_of(x), _spec_of(y)
    tx = bool(kwargs.get("transpose_x", False) or
              (len(args) > 2 and args[2]))
    ty = bool(kwargs.get("transpose_y", False) or
              (len(args) > 3 and args[3]))
    out = out_vals[0]
    nd = out.ndim
    if x._value.ndim < 2 or y._value.ndim < 2 or nd < 2:
        # matrix-vector / vector products: stay unannotated rather
        # than risk assigning the m-dim sharding to a batch dim
        return None
    parts: List = [None] * nd
    if sx is not None:
        # batch dims + m
        for i in range(min(x._value.ndim - 2, nd - 2)):
            parts[i] = sx[i]
        parts[-2] = sx[-1] if tx else sx[-2]
    if sy is not None:
        parts[-1] = sy[-2] if ty else sy[-1]
    return [tuple(parts)]


@register_rule("linear", "fused_gemm_epilogue")
def _linear(op, in_ts, out_vals, args, kwargs):
    x, w = in_ts[0], in_ts[1]
    sx, sw = _spec_of(x), _spec_of(w)
    nd = out_vals[0].ndim
    parts: List = [None] * nd
    if sx is not None:
        for i in range(nd - 1):
            if i < len(sx) - 1:
                parts[i] = sx[i]
    if sw is not None:
        parts[-1] = sw[-1]
    return [tuple(parts)]


@register_rule("sum", "mean", "max", "min", "prod", "logsumexp")
def _reduction(op, in_ts, out_vals, args, kwargs):
    """(reference reduction.cc) drop reduced dims' entries."""
    t = in_ts[0]
    s = _spec_of(t)
    if s is None:
        return None
    axis = kwargs.get("axis", args[1] if len(args) > 1 else None)
    keepdim = bool(kwargs.get("keepdim", args[2] if len(args) > 2
                              else False))
    nd = t._value.ndim
    if axis is None:
        axes = set(range(nd))
    else:
        axes = {a % nd for a in
                (axis if isinstance(axis, (list, tuple)) else [axis])}
    parts = []
    for i, e in enumerate(s):
        if i in axes:
            if keepdim:
                parts.append(None)
        else:
            parts.append(e)
    return [tuple(parts)]


@register_rule("transpose")
def _transpose(op, in_ts, out_vals, args, kwargs):
    s = _spec_of(in_ts[0])
    if s is None:
        return None
    perm = kwargs.get("perm", args[1] if len(args) > 1 else None)
    if perm is None:
        return [tuple(reversed(s))]
    return [tuple(s[int(p)] for p in perm)]


@register_rule("reshape")
def _reshape(op, in_ts, out_vals, args, kwargs):
    """(reference reshape.cc) keep leading-dim entries while the
    cumulative products still match; anything past the first changed
    dim is conservatively unannotated."""
    t = in_ts[0]
    s = _spec_of(t)
    if s is None:
        return None
    ishape = tuple(t._value.shape)
    oshape = tuple(out_vals[0].shape)
    parts: List = [None] * len(oshape)
    for i in range(min(len(ishape), len(oshape))):
        if ishape[i] != oshape[i]:
            break
        parts[i] = s[i]
    return [tuple(parts)]


@register_rule("squeeze")
def _squeeze(op, in_ts, out_vals, args, kwargs):
    s = _spec_of(in_ts[0])
    if s is None:
        return None
    t = in_ts[0]
    ishape = tuple(t._value.shape)
    axis = kwargs.get("axis", args[1] if len(args) > 1 else None)
    nd = len(ishape)
    if axis is None:
        drop = {i for i, d in enumerate(ishape) if d == 1}
    else:
        drop = {a % nd for a in
                (axis if isinstance(axis, (list, tuple)) else [axis])}
    return [tuple(e for i, e in enumerate(s) if i not in drop)]


@register_rule("unsqueeze")
def _unsqueeze(op, in_ts, out_vals, args, kwargs):
    s = _spec_of(in_ts[0])
    if s is None:
        return None
    axis = kwargs.get("axis", args[1] if len(args) > 1 else 0)
    axes = sorted((a if a >= 0 else a + out_vals[0].ndim)
                  for a in (axis if isinstance(axis, (list, tuple))
                            else [axis]))
    parts = list(s)
    for a in axes:
        parts.insert(a, None)
    return [tuple(parts)]


@register_rule("embedding", "c_embedding")
def _embedding(op, in_ts, out_vals, args, kwargs):
    """(reference embedding.cc) out = ids dims + table's embed dim."""
    # signature embedding(x, weight) / c_embedding(w, ids)
    if op == "c_embedding":
        w, ids = in_ts[0], in_ts[1]
    else:
        ids, w = in_ts[0], in_ts[1]
    si = _spec_of(ids) or (None,) * ids._value.ndim
    sw = _spec_of(w)
    tail = sw[-1] if sw is not None else None
    return [tuple(si) + (tail,)]


@register_rule("flash_attention", "scaled_dot_product_attention")
def _attention(op, in_ts, out_vals, args, kwargs):
    """(reference FlashAttInferSpmd) output follows q."""
    s = _spec_of(in_ts[0])
    return [s] if s is not None else None


@register_rule("softmax", "log_softmax")
def _softmax(op, in_ts, out_vals, args, kwargs):
    s = _spec_of(in_ts[0])
    if s is None:
        return None
    axis = kwargs.get("axis", args[1] if len(args) > 1 else -1)
    nd = in_ts[0]._value.ndim
    parts = list(s)
    parts[axis % nd] = None  # softmax dim must not stay sharded
    return [tuple(parts)]


@register_rule("concat_op", "concat")
def _concat(op, in_ts, out_vals, args, kwargs):
    axis = kwargs.get("axis", 0)
    specs = [_spec_of(t) for t in in_ts if t is not None]
    specs = [s for s in specs if s is not None]
    if not specs:
        return None
    nd = out_vals[0].ndim
    parts: List = [None] * nd
    for i in range(nd):
        vals = [s[i] for s in specs]
        e = vals[0]
        for v in vals[1:]:
            e = _merge_entry(e, v)
        parts[i] = e
    parts[axis % nd] = None
    return [tuple(parts)]


_ELEMENTWISE_OPS = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "pow", "floor_divide", "mod", "remainder", "where", "clip",
    "add_n", "scale",
}

_UNARY_OPS = {
    "relu", "relu6", "gelu", "silu", "swish", "mish", "sigmoid", "tanh",
    "exp", "log", "sqrt", "rsqrt", "abs", "neg", "cast", "dropout",
    "erf", "floor", "ceil", "round", "sign", "square", "leaky_relu",
    "elu", "selu", "celu", "hardswish", "hardsigmoid", "softplus",
    "layer_norm", "rms_norm", "group_norm", "label_smooth",
    "fused_layer_norm_residual", "tril", "triu",
}


def infer(op_name: str, in_tensors: Sequence, out_tensors: Sequence,
          args, kwargs) -> None:
    """Annotate ``out_tensors``' dist_attr from inputs (best-effort; a
    missing/failed rule leaves outputs unannotated)."""
    ts = [t for t in in_tensors if t is not None]
    if not any(getattr(t, "dist_attr", None) is not None for t in ts):
        return
    rule = _RULES.get(op_name)
    if rule is None:
        if op_name in _ELEMENTWISE_OPS:
            rule = _elementwise
        elif op_name in _UNARY_OPS:
            rule = _passthrough_same_shape
        else:
            return
    try:
        out_vals = [o._value for o in out_tensors]
        specs = rule(op_name, ts, out_vals, args, kwargs)
    except Exception:
        return  # metadata only: never break the op over a rule bug
    if not specs:
        return
    for o, s in zip(out_tensors, specs):
        if s is not None and any(e is not None for e in s):
            o.dist_attr = P(*s)
