"""Auto-parallel Engine: train an UNMODIFIED model from parameter
shardings alone.

(reference: python/paddle/distributed/auto_parallel/static/engine.py:848
— there, completion/planner/partitioner passes walk the static Program,
run per-op SPMD rules, insert reshard ops and emit per-rank programs.)

TPU-native redesign: all of that IS XLA's GSPMD pass. The Engine takes a
model whose parameters were annotated with ``shard_tensor`` (or carry
``dist_attr`` PartitionSpecs), jit-compiles loss+backward+optimizer as
ONE program with the parameter/state shardings pinned via
``in_shardings``/``out_shardings``, and lets GSPMD propagate shardings
through every op and insert the minimal collectives — the planner,
partitioner and reshard passes collapse into the compiler. No
Column/RowParallel layer rewrites, no shard_map, no hand-placed
collectives: the plain dense model code runs Megatron-style TP (or any
layout the annotations imply) with loss parity against single-device
execution.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...core.enforce import enforce
from ...tensor import Tensor
from ..engine import bind_params, param_spec

__all__ = ["Engine"]


class Engine:
    """``Engine(model, loss_fn, optimizer, mesh).fit/train_batch`` —
    semi-auto data flow: annotate parameters, everything else is
    inferred (reference Engine.fit/evaluate/predict surface)."""

    def __init__(self, model, loss_fn: Optional[Callable] = None,
                 optimizer=None, mesh: Optional[Mesh] = None,
                 strategy=None, batch_spec: P = P()):
        from .api import ProcessMesh

        if isinstance(mesh, ProcessMesh):
            mesh = mesh.jax_mesh
        if mesh is None:
            from .. import fleet as _fleet

            hcg = _fleet.get_hybrid_communicate_group()
            enforce(hcg is not None, "Engine needs a mesh (pass one or "
                    "fleet.init first)")
            mesh = hcg.mesh
        self.mesh = mesh
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.batch_spec = batch_spec
        self.params: List = list(model.parameters())
        self.trainable = [p for p in self.params if p.trainable]
        self._step_count = 0
        self._compiled: Dict[Any, Any] = {}
        # pin every parameter to its annotated sharding now (replicated
        # when unannotated) — GSPMD propagates from these roots
        for p in self.params:
            sh = NamedSharding(mesh, param_spec(p))
            p._value = jax.device_put(p._value, sh)

    # ------------------------------------------------------------------
    def _sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def _state_specs(self, states):
        """Optimizer slots shaped like the param inherit its spec."""
        specs = []
        for p, st in zip(self.trainable, states):
            ps = param_spec(p)
            specs.append({
                k: ps if getattr(v, "shape", ()) == tuple(
                    p._value.shape) else P()
                for k, v in st.items()})
        return specs

    def _build(self, treedef, leaf_shapes):
        model, opt, loss_fn = self.model, self.optimizer, self.loss_fn
        params, trainable = self.params, self.trainable

        def step(pvals, states, lr, stepc, leaves):
            batch = jax.tree_util.tree_unflatten(treedef, leaves)
            with bind_params(params, pvals):
                loss = loss_fn(model, batch)
                loss.backward()
                grads = tuple(
                    p.grad._value if p.grad is not None
                    else jnp.zeros_like(p._value) for p in trainable)
                for p in trainable:
                    p.grad = None
                    p._grad_node = None
            tvals = tuple(v for p, v in zip(params, pvals) if p.trainable)
            new_p, new_s = opt._fused_update(tvals, grads, states, lr,
                                             stepc)
            out_p = list(pvals)
            it = iter(new_p)
            out_p = tuple(next(it) if p.trainable else v
                          for p, v in zip(params, out_p))
            return loss._value, out_p, new_s

        pspecs = tuple(param_spec(p) for p in params)
        shapes = opt._state_shapes()
        states = tuple(opt._param_state(p, shapes) for p in trainable)
        sspecs = tuple(self._state_specs(states))
        in_sh = (tuple(self._sharding(s) for s in pspecs),
                 tuple({k: self._sharding(v) for k, v in d.items()}
                       for d in sspecs),
                 self._sharding(P()), self._sharding(P()),
                 tuple(self._sharding(self.batch_spec
                                      if len(sh) > 0 else P())
                       for sh in leaf_shapes))
        out_sh = (self._sharding(P()),
                  tuple(self._sharding(s) for s in pspecs),
                  tuple({k: self._sharding(v) for k, v in d.items()}
                        for d in sspecs))
        return jax.jit(step, in_shardings=in_sh, out_shardings=out_sh,
                       donate_argnums=(0, 1))

    # ------------------------------------------------------------------
    def train_batch(self, batch) -> float:
        """One fully-compiled auto-parallel train step."""
        enforce(self.loss_fn is not None and self.optimizer is not None,
                "Engine needs loss_fn and optimizer for training")
        opt = self.optimizer
        leaves, treedef = jax.tree_util.tree_flatten(
            batch, is_leaf=lambda x: isinstance(x, Tensor))
        leaf_vals = tuple(v._value if isinstance(v, Tensor)
                          else jnp.asarray(v) for v in leaves)
        key = (treedef, tuple((v.shape, str(v.dtype)) for v in leaf_vals))
        if key not in self._compiled:
            self._compiled[key] = self._build(
                treedef, tuple(v.shape for v in leaf_vals))
        fn = self._compiled[key]

        # states live in opt._states (the single source of truth, like
        # ParallelEngine): inputs are donated, so the refreshed buffers
        # MUST be written back each step or later reads hit deleted
        # arrays / stale moments
        shapes = opt._state_shapes()
        states = tuple(opt._param_state(p, shapes)
                       for p in self.trainable)
        self._step_count += 1
        opt._step_count = self._step_count
        pvals = tuple(p._value for p in self.params)
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        stepc = jnp.asarray(self._step_count, jnp.int32)
        loss, new_p, new_s = fn(pvals, states, lr, stepc, leaf_vals)
        for p, v in zip(self.params, new_p):
            p._value = v
        for p, ns in zip(self.trainable, new_s):
            opt._states[id(p)] = ns
        return loss

    def fit(self, loader, epochs: int = 1, log_freq: int = 0):
        """Reference-parity convenience loop (Engine.fit)."""
        losses = []
        for _ in range(epochs):
            for batch in loader:
                losses.append(float(self.train_batch(batch)))
        return losses

    def predict(self, batch):
        """Compiled forward under the same sharding roots (executable
        cached per input signature, like train_batch)."""
        model, params = self.model, self.params
        leaves, treedef = jax.tree_util.tree_flatten(
            batch, is_leaf=lambda x: isinstance(x, Tensor))
        leaf_vals = tuple(v._value if isinstance(v, Tensor)
                          else jnp.asarray(v) for v in leaves)
        key = ("predict", treedef,
               tuple((v.shape, str(v.dtype)) for v in leaf_vals))
        if key not in self._compiled:
            from ...autograd import no_grad

            def fwd(pvals, leaves):
                b = jax.tree_util.tree_unflatten(treedef, leaves)
                with no_grad(), bind_params(params, pvals):
                    out = model(b) if not isinstance(b, (tuple, list)) \
                        else model(*b)
                return out._value if isinstance(out, Tensor) else out

            self._compiled[key] = jax.jit(fwd)
        pvals = tuple(p._value for p in self.params)
        return Tensor(self._compiled[key](pvals, leaf_vals),
                      stop_gradient=True)
