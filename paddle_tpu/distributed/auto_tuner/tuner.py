"""Auto-tuner: search over hybrid-parallel configurations.

(reference: python/paddle/distributed/auto_tuner/tuner.py + search.py +
prune.py — grid/GBS search over dp/mp/pp/sharding/micro-batch configs by
launching trial jobs, with analytic pruning.)

The ``hbm_gb`` pruning input is no longer validated by faith alone:
the observability memory ledger (``observability/memledger.py``)
measures the real per-device model-state footprint of a running
``ParallelEngine`` (``engine.state_accounting()``, addressable-shard
bytes incl. ZeRO scatter and pp x vpp chunk ownership) and publishes
the analytic-vs-measured gap as the ``paddle_tpu_mem_analytic_drift``
gauge. ``AutoTuner.crosscheck(cfg, measured_gb)`` computes the same
drift for a trial's measured footprint, so a persistent bias in
``estimate_memory_gb`` can be recalibrated instead of silently
mis-pruning configs.
"""
from __future__ import annotations

import itertools
import json
from typing import Callable, Dict, List, Optional

from .cost_model import estimate_memory_gb, estimate_step_time

__all__ = ["AutoTuner", "default_candidates"]


def _factorizations(n: int, dims: int):
    """All tuples of `dims` positive ints whose product is n."""
    if dims == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, dims - 1):
                yield (d,) + rest


def default_candidates(num_devices: int, model: Dict,
                       global_batch: int,
                       tune_sharding: bool = True,
                       tune_quant_comm: bool = False,
                       tune_sharding_stage: bool = True,
                       tune_offload: bool = False) -> List[Dict]:
    """Valid (dp, mp, pp, sharding, micro) configs for the device count,
    pruned by divisibility (reference prune.py rules).

    ``tune_quant_comm``: additionally emit each comm-bearing config
    with the int8 quantized-collective knob on
    (``quant_comm={"dtype": "int8", ...}`` — distributed/quant_comm.py;
    the cost model prices both the ~0.26x wire bytes and the f32
    error-feedback residual HBM, so quantized configs rank/prune on
    their real trade).

    ``tune_sharding_stage``: additionally emit each sharding-bearing
    config with ``sharding_stage=3`` (ZeRO-3 shard-only parameter
    storage + just-in-time gather, engine._ZeroPlan store_sharded):
    the memory model divides param+grad bytes by the sharding degree
    and the cost model prices the per-step (sh-1)/sh param all-gather,
    so stage 3 surfaces exactly when the stage-2 image doesn't fit —
    the real scale axis the search must be able to reach.

    ``tune_offload``: additionally emit each stage-3 config with the
    host memory tier on (``offload={"optimizer": True, ...}`` —
    distributed/host_offload.py): the memory model drops the offloaded
    optimizer/EF bytes from the HBM image and the cost model charges
    the host DMA page-out leg, so the offload variant surfaces exactly
    when the stage-3 image itself doesn't fit ``hbm_gb`` — the tier
    beyond the last on-chip scale axis."""
    heads = model.get("num_heads", 1)
    layers = model["num_layers"]
    vocab = model.get("vocab_size", 0)
    out = []
    dims = 4 if tune_sharding else 3
    for fact in _factorizations(num_devices, dims):
        if tune_sharding:
            dp, mp, pp, sh = fact
        else:
            dp, mp, pp = fact
            sh = 1
        if heads % mp or (vocab and vocab % mp):
            continue
        if layers % pp:
            continue
        data_ways = dp * sh
        if global_batch % data_ways:
            continue
        per_rank = global_batch // data_ways
        for micro in {1, 2, 4, 8, per_rank}:
            if micro > per_rank or per_rank % micro:
                continue
            cfg = {"dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                   "sharding_degree": sh, "micro_batch_size": micro,
                   "accumulate_steps": per_rank // micro}
            out.append(cfg)
            # stage-3 variant only where a sharding group exists to
            # scatter the parameter image over
            if tune_sharding_stage and sh > 1:
                out.append(dict(cfg, sharding_stage=3))
                # host tier rides the stage-3 variant: offload is the
                # axis past stage 3, never a substitute for it
                if tune_offload:
                    out.append(dict(cfg, sharding_stage=3, offload={
                        "optimizer": True, "prefetch_buckets": 2}))
            # quantized variant only where there is comm to compress
            if tune_quant_comm and (dp * sh > 1 or mp > 1):
                out.append(dict(cfg, quant_comm={
                    "dtype": "int8", "grad_sync": True,
                    "mp_rings": True, "error_feedback": True,
                    "chunk": 256}))
    return out


class AutoTuner:
    """Prunes by the memory model, ranks by the cost model, optionally
    runs measured trials (reference tuner.py loop).

    Usage::

        tuner = AutoTuner(model_cfg, num_devices=64, global_batch=512,
                          seq_len=2048, hbm_gb=95)
        best = tuner.tune(trial_fn=my_run)   # or .best_by_model()
    """

    def __init__(self, model: Dict, num_devices: int, global_batch: int,
                 seq_len: int, hbm_gb: float = 95.0,
                 peak_flops: float = 459e12, recompute: bool = False,
                 candidates: Optional[List[Dict]] = None,
                 max_trials: int = 16, tune_quant_comm: bool = False,
                 tune_sharding_stage: bool = True,
                 tune_offload: bool = False):
        self.model = model
        self.num_devices = num_devices
        self.global_batch = global_batch
        self.seq_len = seq_len
        self.hbm_gb = hbm_gb
        self.peak_flops = peak_flops
        self.recompute = recompute
        self.max_trials = max_trials
        self.tune_quant_comm = tune_quant_comm
        self.tune_sharding_stage = tune_sharding_stage
        self.tune_offload = tune_offload
        self.history: List[Dict] = []
        self._candidates = candidates

    # -- search space ---------------------------------------------------
    def candidates(self) -> List[Dict]:
        if self._candidates is None:
            self._candidates = default_candidates(
                self.num_devices, self.model, self.global_batch,
                tune_quant_comm=self.tune_quant_comm,
                tune_sharding_stage=self.tune_sharding_stage,
                tune_offload=self.tune_offload)
        return self._candidates

    def pruned(self) -> List[Dict]:
        """Configs that fit the memory budget, best-predicted first."""
        fits = []
        for cfg in self.candidates():
            mem = estimate_memory_gb(self.model, cfg, self.global_batch,
                                     self.seq_len,
                                     recompute=self.recompute)
            if mem <= self.hbm_gb:
                t = estimate_step_time(self.model, cfg, self.global_batch,
                                       self.seq_len, self.peak_flops)
                fits.append((t, mem, cfg))
        fits.sort(key=lambda x: x[0])
        return [dict(cfg, _pred_time=t, _pred_mem_gb=mem)
                for t, mem, cfg in fits]

    def crosscheck(self, cfg: Dict, measured_gb: float) -> float:
        """Relative drift of the analytic memory model against a
        measured per-chip footprint: (analytic - measured) / measured
        (positive = the model over-estimates, i.e. prunes configs that
        would actually fit). The live counterpart is the
        ``paddle_tpu_mem_analytic_drift`` gauge
        (observability/memledger.account_engine)."""
        pred = estimate_memory_gb(self.model, cfg, self.global_batch,
                                  self.seq_len,
                                  recompute=self.recompute)
        return (pred - measured_gb) / max(measured_gb, 1e-9)

    def best_by_model(self) -> Dict:
        ranked = self.pruned()
        if not ranked:
            raise RuntimeError(
                "no config fits the memory budget — enable recompute / "
                "sharding or add devices")
        return ranked[0]

    # -- measured trials -------------------------------------------------
    def tune(self, trial_fn: Optional[Callable[[Dict], float]] = None
             ) -> Dict:
        """Run up to max_trials measured trials (``trial_fn(cfg)`` returns
        throughput, higher better; exceptions = OOM/failure → pruned).
        Without a trial_fn, returns the model-predicted best."""
        ranked = self.pruned()
        if trial_fn is None:
            return self.best_by_model()
        best, best_metric = None, -float("inf")
        for cfg in ranked[:self.max_trials]:
            try:
                metric = float(trial_fn({k: v for k, v in cfg.items()
                                         if not k.startswith("_")}))
                status = "ok"
            except Exception as e:  # OOM or crash: record and move on
                metric, status = -float("inf"), f"failed: {e}"
            self.history.append(dict(cfg, metric=metric, status=status))
            if metric > best_metric:
                best, best_metric = cfg, metric
        if best is None:
            raise RuntimeError("all trials failed")
        return best

    def save_history(self, path: str):
        with open(path, "w") as f:
            json.dump(self.history, f, indent=2)
