"""Analytic cost & memory models for hybrid-parallel config pruning.

(reference: python/paddle/distributed/auto_tuner/cost_model.py +
memory_cost_model.py — per-config step-time and HBM estimates used to
prune the search space before launching trials.)

Transformer-shaped models only (the tuner's target); constants are
calibratable but the *ordering* of configs is what pruning needs.
``estimate_memory_gb`` is cross-checked at runtime against the
measured model-state accounting (observability/memledger.py —
``paddle_tpu_mem_analytic_drift``), so its bias is observable.
"""
from __future__ import annotations

from typing import Dict

__all__ = ["estimate_memory_gb", "estimate_step_time"]


def _num_params(model: Dict) -> float:
    h = model["hidden_size"]
    L = model["num_layers"]
    V = model.get("vocab_size", 50304)
    i = model.get("intermediate_size", 4 * h)
    return V * h + L * (4 * h * h + 2 * h * i) + 2 * h


def estimate_memory_gb(model: Dict, cfg: Dict, global_batch: int,
                       seq_len: int, dtype_bytes: int = 2,
                       optimizer_mult: float = 6.0,
                       recompute: bool = False) -> float:
    """Per-chip HBM estimate (params + grads + optimizer + activations).

    optimizer_mult: bytes per param beyond weights (Adam fp32 moments +
    master weights ≈ 12 over bf16 weights of 2 → default 6x weight bytes).
    """
    dp = cfg.get("dp_degree", 1)
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    sh = cfg.get("sharding_degree", 1)
    micro = cfg.get("micro_batch_size",
                    max(1, global_batch // max(1, dp * sh)))
    P = _num_params(model) / (mp * pp)
    param_bytes = P * dtype_bytes
    grad_bytes = P * dtype_bytes
    opt_bytes = P * dtype_bytes * optimizer_mult / sh
    if cfg.get("sharding_stage", 1) >= 3:
        param_bytes /= sh
        grad_bytes /= sh
    h = model["hidden_size"]
    L = model["num_layers"] / pp
    act_per_layer = micro * seq_len * h * dtype_bytes
    act_mult = 4 if recompute else 34  # flash-attn era per-layer factor
    act_bytes = L * act_per_layer * act_mult / mp
    # quant_comm error-feedback residuals (distributed/quant_comm.py):
    # one f32 bucket-payload-sized buffer per signature group — in
    # total, the locally-bucketed grad set once over in fp32. Real HBM
    # the measured accounting (memledger account_engine) reports as
    # the quant_residual component; modeling it here keeps
    # paddle_tpu_mem_analytic_drift flat when the knob turns on.
    quant = cfg.get("quant_comm") or {}
    quant_bytes = 0.0
    if quant.get("dtype", "none") in ("int8", "fp8") and \
            quant.get("error_feedback", True):
        quant_bytes = _num_params(model) / (mp * pp) * 4
    # host offload tier (distributed/host_offload.py): offloaded slots
    # live in host memory BETWEEN steps, so their steady-state bytes
    # leave the HBM image. optimizer: the moment/master shard plus the
    # quant EF residual; params: the stored parameter image (the shard
    # under stage 3). The measured counterpart is memledger's
    # host_state component, which account_engine subtracts from the
    # device total before the drift comparison — the same subtraction
    # keeps the analytic drift flat when the knob turns on.
    off = cfg.get("offload") or {}
    if off.get("optimizer", False):
        opt_bytes = 0.0
        quant_bytes = 0.0
    if off.get("params", False):
        param_bytes = 0.0
    return (param_bytes + grad_bytes + opt_bytes + act_bytes
            + quant_bytes) / 1e9


def estimate_step_time(model: Dict, cfg: Dict, global_batch: int,
                       seq_len: int, peak_flops: float = 459e12,
                       ici_bw: float = 9e10,
                       host_dma_bw: float = 5e10) -> float:
    """Relative step-time: MXU compute + mp/pp/dp comm terms."""
    dp = cfg.get("dp_degree", 1)
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    sh = cfg.get("sharding_degree", 1)
    n = dp * mp * pp * sh
    P = _num_params(model)
    tokens = global_batch * seq_len
    compute = 6.0 * P * tokens / (n * peak_flops * 0.5)
    h = model["hidden_size"]
    L = model["num_layers"]
    micro_tokens = tokens / max(1, dp * sh)
    # quant_comm wire compression: int8/fp8 payload + bf16 per-chunk
    # scales over the model's bf16 baseline bytes
    quant = cfg.get("quant_comm") or {}
    q_on = quant.get("dtype", "none") in ("int8", "fp8")
    q_ratio = (1.0 + 2.0 / float(quant.get("chunk", 256) or 256)) / 2.0
    r_mp = q_ratio if (q_on and quant.get("mp_rings", True)) else 1.0
    r_dp = q_ratio if (q_on and quant.get("grad_sync", True)) else 1.0
    # mp: 4 allreduces of activations per layer
    comm_mp = 0.0 if mp == 1 else \
        4 * L * micro_tokens * h * 2 * 2 * (mp - 1) / mp / ici_bw * r_mp
    # dp/sharding: grad reduce of the param shard
    comm_dp = 0.0 if dp * sh == 1 else \
        2 * (P / (mp * pp)) * 2 * (dp * sh - 1) / (dp * sh) / ici_bw \
        * r_dp
    # stage-3 just-in-time param all-gather at forward entry: per
    # participant (sh-1) x the stored shard bytes
    # (distributed/grad_buckets.py BucketPlan.gather — the comm ledger
    # pins the same closed form). Priced against the quantized
    # param_gather wire when the knob compresses it. Stage 1/2's
    # post-update shard gather moves the same bytes but overlaps the
    # next step's forward on the donated path, so only stage 3 carries
    # the term here — the ORDERING between stages is what pruning needs.
    r_pg = q_ratio if (q_on and quant.get("param_gather", True)) else 1.0
    comm_gather = 0.0
    if cfg.get("sharding_stage", 1) >= 3 and sh > 1:
        comm_gather = (P / (mp * pp)) * 2 * (sh - 1) / sh / ici_bw * r_pg
    # host offload tier (distributed/host_offload.py): each step moves
    # the offloaded state over the host DMA path twice (h2d prefetch +
    # d2h page-out). With prefetch_buckets > 0 the h2d leg overlaps the
    # previous step's tail (goodput books it as overlapped_seconds), so
    # only the page-out leg stays on the critical path — the tuner must
    # see offload as CHEAPER-memory-for-DMA-time, never free.
    off = cfg.get("offload") or {}
    comm_host = 0.0
    if off.get("optimizer", False) or off.get("params", False):
        host_bytes = 0.0
        P_local = P / (mp * pp)
        if off.get("optimizer", False):
            host_bytes += P_local * 12.0 / sh      # fp32 moments+masters
            quant = cfg.get("quant_comm") or {}
            if quant.get("dtype", "none") in ("int8", "fp8") and \
                    quant.get("error_feedback", True):
                host_bytes += P_local * 4.0        # EF residual
        if off.get("params", False):
            stored = P_local * 2.0
            if cfg.get("sharding_stage", 1) >= 3:
                stored /= sh
            host_bytes += stored
        legs = 1.0 if int(off.get("prefetch_buckets", 0) or 0) > 0 \
            else 2.0
        comm_host = legs * host_bytes / host_dma_bw
    # pp: bubble fraction
    acc = cfg.get("accumulate_steps", max(1, 2 * pp))
    bubble = (pp - 1) / max(1, acc + pp - 1)
    return (compute + comm_mp + comm_dp + comm_gather + comm_host) \
        / max(1e-9, 1 - bubble)
