from .tuner import AutoTuner, default_candidates  # noqa: F401
from .cost_model import estimate_memory_gb, estimate_step_time  # noqa: F401

__all__ = ["AutoTuner", "default_candidates", "estimate_memory_gb",
           "estimate_step_time"]
