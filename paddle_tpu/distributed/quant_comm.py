"""Quantized collectives: int8/fp8 wire compression with error feedback.

Every training step moves full-precision bytes over ICI: the bucketed
grad reduce-scatter / DP pmean (distributed/grad_buckets.py) and the
collective-matmul ring ticks (distributed/collective_matmul.py) all
ship fp32/bf16 payloads. EQuARX (PAPERS.md) shows a quantized
all-reduce inside XLA recovers most of that bandwidth with negligible
quality loss; this module is the compile-stable codec + quantized
collective set both call sites plug into, and the comm ledger's
closed-form wire-byte counters make the win measurable even on the
CPU smoke mesh.

**Codec** (``encode``/``decode``): per-chunk symmetric scales over a
fixed chunk lattice. A flat payload of N elements pads with zeros to
``Np = ceil(N/chunk)*chunk``, each chunk gets one scale
``s = max|x| / qmax`` stored as a **bf16 sidecar** (``Np/chunk``
scales), and elements quantize to ``round(x/s)`` in int8 (qmax=127) or
cast to fp8 e4m3 (qmax=448) behind the same interface. Wire bytes for
one payload are therefore exactly::

    Np * 1  +  (Np/chunk) * 2        # int8/fp8 payload + bf16 scales

Decoding multiplies by the SAME bf16-rounded scale the encoder used,
so encode→decode is a pure function of (x, chunk) — identical on every
rank, which the error-feedback algebra below relies on. A chunk of
zeros encodes/decodes to exact zeros (scale 0 → treated as 1); a chunk
holding an inf has scale inf, decoding the whole chunk to NaN so AMP's
found_inf sees the overflow it must see; a NaN amax propagates NaN.
Optional **stochastic rounding** (int8 only): ``floor(x/s + u)`` with
u ~ U[0,1) from an explicit jax PRNG key — unbiased per element, used
by the grad path when the knob asks for it (keys derive from the
step's traced seed + a static site index, so the program is
compile-stable and per-step masks differ).

**Quantized collectives** (the wire movers — every byte goes through
the traced-collective shim so the comm ledger stays exact):

- ``quantized_reduce_scatter(v, axes)``: psum_scatter(v) with int8
  wire. Each rank quantizes its local buffer per DESTINATION row,
  block-exchanges the quantized rows + scales (one all_to_all each),
  dequantizes the p received rows and sums locally — the standard
  reduce-scatter decomposition, same (p-1)/p ring factor, with the
  reduction arithmetic in f32 so quantization error never compounds
  across hops. Also returns the local dequantize(quantize(v)) image
  for the caller's error-feedback residual.
- ``quantized_allreduce(v, axes)``: the EQuARX two-phase form —
  quantized reduce-scatter, then the summed shard re-quantizes and
  all-gathers (int8 + scales again). ``mean=True`` divides by the
  group size at the end (pmean).
- ring-tick helpers (``pack_block``/``unpack_block``/
  ``permute_packed``/``gather_packed``): collective_matmul quantizes a
  block ONCE at ring entry and ships the (payload, scales) pair around
  the ring, dequantizing per tick for the partial GEMM — a payload in
  flight is never re-quantized, so multi-hop shards see exactly one
  quantization. (matmul_rs re-quantizes its accumulator per shift
  because the values change each tick; that error is bounded by one
  quantization step per hop and is the EQuARX trade.)

**Error feedback**: the residual ``e`` is carried per grad bucket as
training state (f32, rank-local). Each step the bucket sync computes
``v = g + e``, puts ``quantize(v)`` on the wire, and stores
``e' = v - decode(encode(v))`` — the compression error re-enters the
next step's gradient instead of being lost, which is what keeps
convergence at fp32 parity (pinned by the deterministic-horizon test).
The residual is REAL state: it joins the engine checkpoint as one
commit unit and a resume that dropped it would be a correctness bug.

Knob: ``strategy.hybrid_configs["quant_comm"]`` —
``{"dtype": "int8"|"fp8"|"none", "grad_sync": bool, "mp_rings": bool,
"param_gather": bool, "chunk": int, "error_feedback": bool,
"stochastic_rounding": bool}`` (defaults off via dtype="none");
``grad_sync`` rides the comm_overlap bucket plan, ``mp_rings`` covers
the collective-matmul rings plus the Megatron TP activation
allreduces, and ``param_gather`` the ZeRO stage-2/3 param all-gather
(own-shard splice — see ``quantized_param_gather``).
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import collective as C
from ..observability import commledger as _cl

__all__ = [
    "QuantConfig", "make_config", "strategy_config", "grad_sync_config",
    "ring_config", "override", "encode", "decode", "padded_len",
    "payload_wire_bytes", "reduce_scatter_wire_bytes",
    "allreduce_wire_bytes", "quantized_reduce_scatter",
    "quantized_allreduce", "quantized_param_gather",
    "maybe_quantized_psum", "pack_block", "unpack_block",
    "block_ratio", "permute_packed", "gather_packed", "site_key",
    "DEFAULTS", "SCALE_BYTES",
]

# the reference knob surface (merged into DistributedStrategy's
# hybrid_configs defaults); dtype "none" = everything off
DEFAULTS: Dict[str, Any] = {
    "dtype": "none",
    "grad_sync": True,
    "mp_rings": True,
    "param_gather": True,
    "chunk": 256,
    "error_feedback": True,
    "stochastic_rounding": False,
}

_QMAX = {"int8": 127.0, "fp8": 448.0}
SCALE_DTYPE = jnp.bfloat16
SCALE_BYTES = 2     # bf16 sidecar
WIRE_ITEMSIZE = 1   # int8 and fp8 e4m3 are both one byte


@dataclass(frozen=True)
class QuantConfig:
    """One resolved quant_comm knob set (hashable, trace-static)."""

    dtype: str = "none"
    grad_sync: bool = True
    mp_rings: bool = True
    param_gather: bool = True
    chunk: int = 256
    error_feedback: bool = True
    stochastic_rounding: bool = False

    @property
    def enabled(self) -> bool:
        return self.dtype in _QMAX

    @property
    def wire_dtype(self):
        return jnp.int8 if self.dtype == "int8" else jnp.float8_e4m3fn

    @property
    def qmax(self) -> float:
        return _QMAX[self.dtype]


def make_config(cfg) -> QuantConfig:
    """Validate + freeze a knob dict (or pass a QuantConfig through)."""
    if cfg is None:
        return QuantConfig()
    if isinstance(cfg, QuantConfig):
        return cfg
    from ..core.enforce import enforce

    unknown = set(cfg) - set(DEFAULTS)
    enforce(not unknown,
            f"quant_comm: unknown keys {sorted(unknown)} "
            f"(valid: {sorted(DEFAULTS)})")
    merged = dict(DEFAULTS)
    merged.update(cfg)
    enforce(merged["dtype"] in ("none", "int8", "fp8"),
            f"quant_comm dtype must be 'int8', 'fp8' or 'none', got "
            f"{merged['dtype']!r}")
    enforce(int(merged["chunk"]) > 0,
            f"quant_comm chunk must be positive, got {merged['chunk']}")
    return QuantConfig(
        dtype=str(merged["dtype"]),
        grad_sync=bool(merged["grad_sync"]),
        mp_rings=bool(merged["mp_rings"]),
        param_gather=bool(merged["param_gather"]),
        chunk=int(merged["chunk"]),
        error_feedback=bool(merged["error_feedback"]),
        stochastic_rounding=bool(merged["stochastic_rounding"]))


# test/bench hook: force a config without a fleet strategy (the engine
# constructor override serves the grad path; this one serves the rings)
_override: list = []


@contextlib.contextmanager
def override(cfg):
    """Force ``strategy_config()`` to return ``cfg`` inside the block
    (tests / engines built without fleet.init)."""
    _override.append(make_config(cfg))
    try:
        yield
    finally:
        _override.pop()


def strategy_config(strategy=None) -> QuantConfig:
    """The active quant_comm knob set, from the fleet strategy's
    ``hybrid_configs["quant_comm"]`` (the reference knob surface), or
    the all-off defaults when no strategy is active."""
    if _override:
        return _override[-1]
    if strategy is None:
        from . import fleet as _fleet

        strategy = _fleet.get_strategy()
    if strategy is None:
        return QuantConfig()
    return make_config(strategy.hybrid_configs.get("quant_comm") or {})


def grad_sync_config(strategy=None) -> Optional[QuantConfig]:
    cfg = strategy_config(strategy)
    return cfg if (cfg.enabled and cfg.grad_sync) else None


def ring_config(strategy=None) -> Optional[QuantConfig]:
    cfg = strategy_config(strategy)
    return cfg if (cfg.enabled and cfg.mp_rings) else None


# ---------------------------------------------------------------------------
# codec: per-chunk symmetric scales over the fixed chunk lattice
# ---------------------------------------------------------------------------


def padded_len(n: int, chunk: int) -> int:
    """The chunk-lattice length a flat payload of ``n`` pads to."""
    return -(-int(n) // int(chunk)) * int(chunk)


def payload_wire_bytes(n: int, cfg: QuantConfig) -> int:
    """Exact wire bytes of one encoded payload of ``n`` elements:
    ceil-padded 1-byte lattice + the bf16 scale sidecar."""
    np_ = padded_len(n, cfg.chunk)
    return np_ * WIRE_ITEMSIZE + (np_ // cfg.chunk) * SCALE_BYTES


def _scale32(s):
    """The f32 scale decode (and encode) divide/multiply by, derived
    from the stored bf16 sidecar: 0 → 1 (all-zero chunk), NaN/inf
    propagate so nonfinite inputs stay visible to AMP's found_inf."""
    s32 = s.astype(jnp.float32)
    return jnp.where(s32 == 0.0, jnp.float32(1.0), s32)


def encode(x, cfg: QuantConfig, key=None):
    """Quantize ``x`` ([..., L] with L % chunk == 0) on the chunk
    lattice. Returns ``(payload, scales)``: payload in the wire dtype
    with x's shape, scales bf16 [..., L/chunk]."""
    chunk = cfg.chunk
    xs = x.astype(jnp.float32)
    g = xs.reshape(xs.shape[:-1] + (xs.shape[-1] // chunk, chunk))
    amax = jnp.max(jnp.abs(g), axis=-1)
    s = (amax / cfg.qmax).astype(SCALE_DTYPE)
    scaled = g / _scale32(s)[..., None]
    if cfg.dtype == "int8":
        if cfg.stochastic_rounding and key is not None:
            u = jax.random.uniform(key, scaled.shape,
                                   dtype=jnp.float32)
            qv = jnp.floor(scaled + u)
        else:
            qv = jnp.round(scaled)
        q = jnp.clip(qv, -cfg.qmax, cfg.qmax).astype(jnp.int8)
    else:
        q = jnp.clip(scaled, -cfg.qmax,
                     cfg.qmax).astype(jnp.float8_e4m3fn)
    return q.reshape(x.shape), s


def decode(q, s, cfg: QuantConfig, dtype=jnp.float32):
    """Dequantize an ``encode`` pair back to ``dtype`` (x's shape)."""
    chunk = cfg.chunk
    g = q.astype(jnp.float32).reshape(
        q.shape[:-1] + (q.shape[-1] // chunk, chunk))
    out = g * _scale32(s)[..., None]
    return out.reshape(q.shape).astype(dtype)


# ---------------------------------------------------------------------------
# quantized collectives (all wire movement through the ledger shim)
# ---------------------------------------------------------------------------


def _group_size(axes) -> int:
    p = 1
    for a in axes:
        p *= int(C.axis_size(a))
    return p


def _pad_rows(rows, L: int, Lp: int):
    return rows if Lp == L else jnp.pad(rows, ((0, 0), (0, Lp - L)))


def reduce_scatter_wire_bytes(n: int, p: int, cfg: QuantConfig,
                              trips: int = 1) -> float:
    """Closed-form per-participant wire bytes of ONE quantized
    reduce-scatter of an ``n``-element payload over a group of ``p``:
    the (p-1)/p-factored all_to_all of the int8 rows plus the bf16
    scale sidecar (see quantized_reduce_scatter)."""
    L = n // p
    Lp = padded_len(L, cfg.chunk)
    nc = Lp // cfg.chunk
    return float((p - 1) * (Lp * WIRE_ITEMSIZE + nc * SCALE_BYTES)
                 * trips)


def allreduce_wire_bytes(n: int, p: int, cfg: QuantConfig,
                         trips: int = 1) -> float:
    """Closed-form per-participant wire bytes of ONE quantized
    allreduce (reduce-scatter phase + all-gather phase, both int8 +
    bf16 scales)."""
    L = -(-int(n) // p)
    Lp = padded_len(L, cfg.chunk)
    nc = Lp // cfg.chunk
    per_phase = (p - 1) * (Lp * WIRE_ITEMSIZE + nc * SCALE_BYTES)
    return float(2 * per_phase * trips)


def quantized_reduce_scatter(v, axes, cfg: QuantConfig, key=None,
                             logical_itemsize: int = 4):
    """``psum_scatter(v, axes, scatter_dimension=0, tiled=True)`` with
    int8/fp8 wire. ``v``: f32 flat [N], N % p == 0.

    Returns ``(shard, local_dequant)``: the f32 summed shard [N/p] and
    the local decode(encode(v)) image [N] — ``v - local_dequant`` is
    the caller's error-feedback residual. ``logical_itemsize`` is the
    itemsize the UNQUANTIZED path would have put on the wire (the grad
    dtype) — it prices the ledger's payload_ratio stamp.
    """
    axes = tuple(axes)
    p = _group_size(axes)
    if p <= 1:
        return v, v
    N = int(v.shape[0])
    L = N // p
    Lp = padded_len(L, cfg.chunk)
    rows = _pad_rows(v.reshape(p, L), L, Lp)
    q, s = encode(rows, cfg, key)                # [p, Lp], [p, nc]
    deq = decode(q, s, cfg)[:, :L].reshape(N)
    nc = Lp // cfg.chunk
    ratio = (p * (Lp * WIRE_ITEMSIZE + nc * SCALE_BYTES)) \
        / float(N * logical_itemsize)
    with _cl.quant_wire(ratio):
        qq = C.t_all_to_all(q, axes, split_axis=0, concat_axis=0,
                            tiled=True)
        ss = C.t_all_to_all(s, axes, split_axis=0, concat_axis=0,
                            tiled=True)
    shard = jnp.sum(decode(qq, ss, cfg)[:, :L], axis=0)
    return shard, deq


def quantized_allreduce(v, axes, cfg: QuantConfig, mean: bool = False,
                        key=None, logical_itemsize: int = 4):
    """``psum(v, axes)`` (or pmean with ``mean=True``) with int8/fp8
    wire: quantized reduce-scatter + re-quantized all-gather (the
    EQuARX two-phase form). ``v``: f32 flat [N], any N.

    Returns ``(full, local_dequant)`` with ``full`` f32 [N] and
    ``local_dequant`` the phase-1 decode(encode(v)) image for error
    feedback (the phase-2 re-quantization of the already-summed shard
    is stateless — its error is not locally attributable).
    """
    axes = tuple(axes)
    p = _group_size(axes)
    if p <= 1:
        return v, v
    N = int(v.shape[0])
    L = -(-N // p)
    Lp = padded_len(L, cfg.chunk)
    Np = p * Lp
    vp = jnp.pad(v, (0, Np - N)) if Np != N else v
    nc = Lp // cfg.chunk
    # phase-1 ratio prices the rs phase against HALF the fp psum wire
    # ((p-1)/p * N * itemsize); phase 2 against the other half — the
    # expression is the same, so one stamp covers all four records
    ratio = (p * (Lp * WIRE_ITEMSIZE + nc * SCALE_BYTES)) \
        / float(N * logical_itemsize)
    rows = vp.reshape(p, Lp)
    q, s = encode(rows, cfg, key)
    deq = decode(q, s, cfg).reshape(Np)[:N]
    with _cl.quant_wire(ratio):
        qq = C.t_all_to_all(q, axes, split_axis=0, concat_axis=0,
                            tiled=True)
        ss = C.t_all_to_all(s, axes, split_axis=0, concat_axis=0,
                            tiled=True)
    shard = jnp.sum(decode(qq, ss, cfg), axis=0)     # [Lp] f32
    if key is not None:
        key = jax.random.fold_in(key, 1)
    q2, s2 = encode(shard, cfg, key)
    with _cl.quant_wire(ratio):
        qg = C.t_all_gather(q2[None], axes, axis=0, tiled=True)
        sg = C.t_all_gather(s2[None], axes, axis=0, tiled=True)
    full = decode(qg, sg, cfg).reshape(Np)[:N]
    if mean:
        full = full / p
    return full, deq


# ---------------------------------------------------------------------------
# ring-tick helpers (collective_matmul's per-block quantize/dequantize)
# ---------------------------------------------------------------------------


def pack_block(x, cfg: QuantConfig, key=None):
    """Quantize one ring block (any shape): flatten, pad to the chunk
    lattice, encode. Returns ``(payload [Np], scales [Np/chunk])``."""
    n = int(np.prod(x.shape)) if x.ndim else 1
    Lp = padded_len(n, cfg.chunk)
    flat = x.reshape(-1).astype(jnp.float32)
    if Lp != n:
        flat = jnp.pad(flat, (0, Lp - n))
    return encode(flat, cfg, key)


def unpack_block(q, s, shape, dtype, cfg: QuantConfig):
    """Dequantize a packed ring block back to ``(shape, dtype)``."""
    n = int(np.prod(shape)) if shape else 1
    v = decode(q, s, cfg)
    return v[:n].reshape(shape).astype(dtype)


def block_ratio(shape, dtype, cfg: QuantConfig) -> float:
    """Compressed / uncompressed wire-byte ratio of one packed block —
    the quant_wire stamp for its ppermute/all_gather records."""
    n = int(np.prod(shape)) if shape else 1
    Lp = padded_len(n, cfg.chunk)
    nc = Lp // cfg.chunk
    return (Lp * WIRE_ITEMSIZE + nc * SCALE_BYTES) \
        / float(n * np.dtype(dtype).itemsize)


def permute_packed(q, s, name, perm, ratio: float):
    """ppermute a packed (payload, scales) pair — both records stamped
    with the block's compression ratio."""
    with _cl.quant_wire(ratio):
        return (C.t_ppermute(q, name, perm),
                C.t_ppermute(s, name, perm))


def gather_packed(q, s, axes, ratio: float):
    """all_gather a packed pair along a new leading rank dim:
    [Np] → [p, Np] (+ scales). The caller dequantizes per row and
    reassembles along its own concat axis."""
    with _cl.quant_wire(ratio):
        return (C.t_all_gather(q[None], axes, axis=0, tiled=True),
                C.t_all_gather(s[None], axes, axis=0, tiled=True))


def quantized_param_gather(shard, axes, dim: int, cfg: QuantConfig):
    """The ZeRO stage-2/3 param all-gather with int8/fp8 wire: pack the
    updated shard once, all_gather payload + scales, reassemble rank
    blocks along ``dim`` — then splice this rank's OWN exact shard back
    over its block. The authoritative state path (each rank re-slices
    its own shard for the next update) therefore stays bit-exact and
    quantization error never accumulates in the weights; only the
    OTHER ranks' working copies carry one quantization of noise,
    regenerated fresh from exact shards every step (the MS-AMP/FSDP
    low-precision param all-gather discipline)."""
    from jax import lax

    axes = tuple(axes) if not isinstance(axes, str) else (axes,)
    p = _group_size(axes)
    if p <= 1:
        return shard
    ratio = block_ratio(shard.shape, shard.dtype, cfg)
    q, s = pack_block(shard, cfg)
    qg, sg = gather_packed(q, s, axes, ratio)
    blocks = [unpack_block(qg[j], sg[j], shard.shape, shard.dtype, cfg)
              for j in range(p)]
    full = jnp.concatenate(blocks, axis=dim)
    idx = C.axis_index(axes)
    return lax.dynamic_update_slice_in_dim(
        full, shard, idx * shard.shape[dim], axis=dim)


def maybe_quantized_psum(x, axes):
    """``t_psum(x, axes)`` with int8/fp8 wire when the quant_comm
    mp_rings knob is on (the TP activation allreduces: the Megatron
    psum/identity primitives the embedding and fallback linear paths
    issue). Stateless — activations carry no error-feedback state
    across steps; full-precision shim call otherwise."""
    axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
    cfg = ring_config()
    if cfg is None or _group_size(axes_t) <= 1:
        return C.t_psum(x, axes)
    n = int(np.prod(x.shape)) if x.ndim else 1
    full, _ = quantized_allreduce(
        x.reshape(-1).astype(jnp.float32), axes_t, cfg, mean=False,
        logical_itemsize=int(np.dtype(x.dtype).itemsize))
    return full.reshape(x.shape).astype(x.dtype)


def site_key(cfg: Optional[QuantConfig], site: int):
    """A compile-stable stochastic-rounding key for a static call
    site: derived from the step's traced seed (core/rng fork_traced)
    folded with ``site`` — a pure function of the program position,
    never of host trace count. None when stochastic rounding is off
    (the codec then rounds to nearest)."""
    if cfg is None or not cfg.stochastic_rounding:
        return None
    from ..core import rng as _rng

    seed = _rng.traced_seed()
    base = jax.random.key(seed if seed is not None else jnp.uint32(0))
    return jax.random.fold_in(base, int(site))
