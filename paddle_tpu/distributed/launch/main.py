"""Job launcher: ``python -m paddle_tpu.distributed.launch``.

(reference: python/paddle/distributed/launch/main.py:20 +
controllers/collective.py:37 CollectiveController.build_pod — spawns one
process per GPU with PADDLE_TRAINER_ENDPOINTS / PADDLE_MASTER / rank
envs; controllers/watcher.py liveness monitor.)

TPU-native process model: XLA is single-controller per HOST — one
process drives all local chips (the reference runs one per GPU). So:
- single host, no --nnodes: exec the script in-process (env setup only);
- --nnodes N: this process is one trainer of N; we export the PADDLE_*
  envs and (when available) point jax.distributed at the coordinator so
  multi-host meshes form over DCN;
- --nproc_per_node K (testing / CPU simulation): fork K local trainer
  processes with ranked envs, watch them, propagate the first failure
  (the watcher role).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List

__all__ = ["launch"]


def _parse(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a (multi-host) training job")
    p.add_argument("--master", default=None,
                   help="coordinator host:port (rank-0 host)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of hosts in the job")
    p.add_argument("--rank", type=int, default=None,
                   help="this host's rank (default: from env or 0)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="local trainer processes (testing; TPU uses 1)")
    p.add_argument("--devices", default=None,
                   help="visible device ids, comma separated")
    p.add_argument("--log_dir", default=None, help="per-rank log dir")
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0: fail fast; 1: relaunch the pod on failure "
                        "(trainers must resume from their checkpoint, "
                        "see fleet.elastic.load_train_state)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="relaunch budget under --elastic_level 1")
    p.add_argument("training_script", help="script to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _base_env(args, rank: int, world: int) -> dict:
    env = dict(os.environ)
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    if args.master:
        env["PADDLE_MASTER"] = args.master
    if args.devices is not None:
        env["CUDA_VISIBLE_DEVICES"] = args.devices  # parity name
        env["TPU_VISIBLE_DEVICES"] = args.devices
    env["PADDLE_DISTRI_BACKEND"] = "xla"
    return env


def _watch(procs: List[subprocess.Popen]) -> int:
    """Reference watcher.py: first non-zero exit kills the pod."""
    try:
        while True:
            alive = False
            for i, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    return rc
            if not alive:
                return 0
            time.sleep(0.2)
    except KeyboardInterrupt:
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)
        return 130


def launch(argv=None) -> int:
    args = _parse(argv)
    world_hosts = args.nnodes
    host_rank = args.rank if args.rank is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    if args.nproc_per_node <= 1:
        # TPU path: ONE process drives all local chips
        env = _base_env(args, host_rank, world_hosts)
        if world_hosts > 1 and args.master:
            # multi-host: jax.distributed coordinator over DCN
            env.setdefault("JAX_COORDINATOR_ADDRESS", args.master)
            env.setdefault("JAX_NUM_PROCESSES", str(world_hosts))
            env.setdefault("JAX_PROCESS_ID", str(host_rank))
        os.environ.update(env)
        cmd = [sys.executable, args.training_script,
               *args.training_script_args]
        return subprocess.call(cmd, env=env)

    # simulation path: K ranked local processes (reference build_pod)
    def build_pod(attempt: int):
        procs = []
        world = args.nproc_per_node * world_hosts
        master = args.master or "127.0.0.1:35127"
        for local in range(args.nproc_per_node):
            rank = host_rank * args.nproc_per_node + local
            env = _base_env(args, rank, world)
            env["PADDLE_MASTER"] = master
            env["PADDLE_LOCAL_RANK"] = str(local)
            env["PADDLE_RESTART_COUNT"] = str(attempt)
            if attempt > 0:
                env["PADDLE_ELASTIC_RESTART"] = "1"
            stdout = None
            if args.log_dir:
                os.makedirs(args.log_dir, exist_ok=True)
                suffix = f".{attempt}" if attempt else ""
                stdout = open(os.path.join(
                    args.log_dir, f"workerlog.{rank}{suffix}"), "w")
            procs.append(subprocess.Popen(
                [sys.executable, args.training_script,
                 *args.training_script_args],
                env=env, stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None))
        return procs

    # elastic relaunch loop (reference elastic/manager.py:237-264: the
    # launcher restarts the pod on world change; trainers resume from
    # their sharded checkpoint — fleet.elastic.load_train_state, tested
    # end-to-end in tests/test_elastic_resume.py)
    attempts = args.max_restarts if args.elastic_level >= 1 else 0
    attempt = 0
    while True:
        rc = _watch(build_pod(attempt))
        if rc == 0 or attempt >= attempts:
            break
        attempt += 1
        print(f"launch: pod failed (rc={rc}); elastic relaunch "
              f"{attempt}/{attempts}", file=sys.stderr)
    if rc != 0:
        print(f"launch: pod failed with exit code {rc}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(launch())
