import sys

from .main import launch

if __name__ == "__main__":
    sys.exit(launch())
