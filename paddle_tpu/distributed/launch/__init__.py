from .main import launch  # noqa: F401

__all__ = ["launch"]
