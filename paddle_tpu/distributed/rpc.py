"""RPC (paddle.distributed.rpc analog).

(reference: python/paddle/distributed/rpc/__init__.py — init_rpc:40,
rpc_sync:118, rpc_async:171, shutdown over a C++ brpc agent
fluid/distributed/rpc/rpc_agent.cc.)

TPU-native scope: device communication is XLA collectives; RPC is the
HOST-side control/side-channel (parameter-server style coordination,
metrics plumbing, custom orchestration). The brpc agent is replaced by
the native TCPStore (csrc/tcp_store.cpp): each worker registers its
name, runs a serving thread that executes pickled (fn, args, kwargs)
requests in arrival order, and responses flow back through the store —
same at-most-once, in-order semantics the reference agent provides per
sender.
"""
from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

from .store import TCPStore, create_or_get_global_tcp_store

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]

_POLL = 0.01


class WorkerInfo:
    def __init__(self, name: str, rank: int):
        self.name = name
        self.rank = rank

    def __repr__(self):
        return f"WorkerInfo(name={self.name!r}, rank={self.rank})"


class _Future:
    """Return handle of rpc_async (reference FutureWrapper)."""

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None

    def _set(self, value=None, exc=None):
        self._value, self._exc = value, exc
        self._ev.set()

    def wait(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("rpc future timed out")
        if self._exc is not None:
            raise self._exc
        return self._value


class _RpcAgent:
    def __init__(self, name: str, rank: int, world_size: int,
                 store: TCPStore):
        self.name = name
        self.rank = rank
        self.world = world_size
        self.store = store
        self._stop = threading.Event()
        self._served = [0] * world_size   # next expected seq PER SENDER
        self._send_seq: Dict[int, int] = {}  # sender-local counters
        store.set(f"rpc/name2rank/{name}", str(rank))
        store.set(f"rpc/rank2name/{rank}", name)
        self._server = threading.Thread(target=self._serve, daemon=True)
        self._server.start()
        store.barrier("rpc/init", world_size)

    # -- serving --------------------------------------------------------
    def _serve(self):
        while not self._stop.is_set():
            progressed = False
            for src in range(self.world):
                key = (f"rpc/req/{self.rank}/{src}/"
                       f"{self._served[src]}")
                if not self.store.check(key):
                    continue
                progressed = True
                self._serve_one(src, key)
            if not progressed:
                time.sleep(_POLL)

    def _serve_one(self, src_expected, key):
        src, seq, fn, args, kwargs = pickle.loads(self.store.get(key))
        try:
            result, exc = fn(*args, **kwargs), None
        except BaseException as e:  # delivered to the caller
            result, exc = None, e
        try:
            payload = pickle.dumps((result, exc), protocol=4)
        except Exception as pe:
            # unpicklable result/exception must not kill the serve
            # loop — deliver a picklable error instead
            payload = pickle.dumps(
                (None, RuntimeError(
                    f"rpc result not picklable: {pe!r}; "
                    f"result={result!r:.200}, exc={exc!r:.200}")),
                protocol=4)
        self.store.set(f"rpc/res/{src}/{self.rank}/{seq}", payload)
        self.store.delete_key(key)
        self._served[src] += 1

    # -- calling --------------------------------------------------------
    def _rank_of(self, to: str) -> int:
        return int(self.store.get(f"rpc/name2rank/{to}", timeout=30))

    def call(self, to: str, fn, args, kwargs, timeout) -> _Future:
        dst = self._rank_of(to)
        # SENDER-LOCAL sequence: no store round-trip to allocate, and a
        # caller dying mid-send can only stall its own stream
        seq = self._send_seq.get(dst, 0)
        self._send_seq[dst] = seq + 1
        self.store.set(f"rpc/req/{dst}/{self.rank}/{seq}", pickle.dumps(
            (self.rank, seq, fn, tuple(args or ()), dict(kwargs or {})),
            protocol=4))
        fut = _Future()

        def waiter():
            key = f"rpc/res/{self.rank}/{dst}/{seq}"
            deadline = None if timeout is None else time.time() + timeout
            while not self.store.check(key):
                if deadline and time.time() > deadline:
                    fut._set(exc=TimeoutError(
                        f"rpc to {to!r} timed out"))
                    return
                time.sleep(_POLL)
            result, exc = pickle.loads(self.store.get(key))
            self.store.delete_key(key)
            fut._set(result, exc)

        threading.Thread(target=waiter, daemon=True).start()
        return fut

    def stop(self):
        self._stop.set()
        self._server.join(timeout=2)


_agent: Optional[_RpcAgent] = None


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None) -> None:
    """(reference rpc/__init__.py:40)"""
    global _agent
    import os

    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if world_size is None:
        world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if master_endpoint:
        os.environ.setdefault("PADDLE_MASTER", master_endpoint)
    _agent = _RpcAgent(name, rank, world_size,
                       create_or_get_global_tcp_store())


def _require_agent() -> _RpcAgent:
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return _agent


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout: Optional[float] = 120.0):
    """Blocking remote call (reference rpc_sync:118)."""
    return _require_agent().call(to, fn, args, kwargs, timeout).wait(
        timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout: Optional[float] = 120.0) -> _Future:
    """Non-blocking remote call returning a Future (rpc_async:171)."""
    return _require_agent().call(to, fn, args, kwargs, timeout)


def get_worker_info(name: str) -> WorkerInfo:
    a = _require_agent()
    return WorkerInfo(name, a._rank_of(name))


def get_all_worker_infos() -> List[WorkerInfo]:
    a = _require_agent()
    infos = []
    for r in range(a.world):
        try:
            nm = a.store.get(f"rpc/rank2name/{r}", timeout=5).decode()
        except Exception:
            continue
        infos.append(WorkerInfo(nm, r))
    return infos


def shutdown() -> None:
    """Barrier + stop serving (reference shutdown)."""
    global _agent
    if _agent is None:
        return
    _agent.store.barrier("rpc/shutdown", _agent.world)
    _agent.stop()
    _agent = None
