from . import moe_utils  # noqa: F401
from .moe_utils import global_gather, global_scatter  # noqa: F401

__all__ = ["moe_utils", "global_scatter", "global_gather"]
