"""MoE all-to-all communication helpers.

TPU-native replacement for the reference's variable-length collectives
(reference: python/paddle/distributed/utils/moe_utils.py:20
global_scatter/global_gather; CUDA
fluid/operators/collective/global_scatter_op.cu.cc — NCCL grouped
send/recv driven by per-(rank,expert) counts).

XLA collectives are compiled with static shapes, so the variable-count
protocol becomes a *uniform-slot* all-to-all: callers lay tokens out as
``[n_expert_total, capacity, d]`` (MoELayer's dense dispatch does this)
and the exchange is one ``lax.all_to_all`` on ICI. The count-based
entry points below therefore require uniform counts; MoELayer never
calls them with anything else.
"""
from __future__ import annotations

import jax
from jax import lax

from .. import collective as C
from ...autograd import engine as _engine
from ...core.enforce import enforce
from ...tensor import Tensor

__all__ = ["global_scatter", "global_gather"]


def _a2a(x: Tensor, axes, split_axis: int, concat_axis: int,
         name: str) -> Tensor:
    val = C.t_all_to_all(x._value, axes, split_axis, concat_axis,
                          tiled=True)
    out = Tensor(val, stop_gradient=x.stop_gradient)
    if _engine.is_grad_enabled() and not x.stop_gradient:
        out.stop_gradient = False

        def bwd(g):
            return (C.t_all_to_all(g, axes, concat_axis, split_axis,
                                    tiled=True),)

        _engine.record_custom(name, bwd, [x], [out], val)
    return out


def _check_uniform(counts, world, name):
    """Reject the variable-length count protocol with an actionable
    error: XLA collectives compile to static shapes, so the NCCL-style
    per-(rank, expert) counts the reference op accepts must all be
    EQUAL here (one fixed capacity per slot)."""
    if counts is None:
        return
    vals = [int(v) for v in
            (counts.numpy() if isinstance(counts, Tensor) else counts)]
    distinct = sorted(set(vals))
    enforce(
        len(distinct) <= 1,
        f"{name}: non-uniform per-rank token counts {vals} "
        f"({len(distinct)} distinct values {distinct}, group size "
        f"{world}). XLA's all_to_all is compiled with a static shape, "
        f"so the reference's variable-length send/recv protocol "
        f"becomes a uniform-slot exchange: every rank must move the "
        f"SAME count per peer. Pad each (rank, expert) slot to a fixed "
        f"capacity C = max(counts) and lay tokens out as "
        f"[n_expert_total, C, d] (MoELayer's dense GShard dispatch "
        f"does exactly this), or route through MoELayer instead of "
        f"calling {name} directly.")


def global_scatter(x: Tensor, local_count=None, global_count=None,
                   group=None, use_calc_stream: bool = True) -> Tensor:
    """Send token slots to the ranks owning their experts
    (reference moe_utils.py:20). ``x``: [E_total*C_local, d] or
    [E_total, C, d]; returns this rank's experts' slots from all ranks."""
    g = group if group is not None else C.get_group(0)
    if g is None or g.nranks <= 1 or not C.in_spmd_region():
        return x
    _check_uniform(local_count, g.nranks, "global_scatter")
    axes = g.axis_names
    squeeze = x.ndim == 2
    if squeeze:
        from ...ops import manipulation as M

        # [n*k, d] -> [n, k, d], then the shape-preserving block
        # exchange (split == concat axis): block j -> rank j. This is
        # an involution, so the gather round trip is the identity.
        n = g.nranks
        x = M.reshape(x, [n, x.shape[0] // n, x.shape[1]])
        out = _a2a(x, axes, 0, 0, "global_scatter")
        return M.reshape(out, [-1, out.shape[-1]])
    return _a2a(x, axes, 0, 1, "global_scatter")


def global_gather(x: Tensor, local_count=None, global_count=None,
                  group=None, use_calc_stream: bool = True) -> Tensor:
    """Inverse of global_scatter: return expert outputs to the token-origin
    ranks (reference moe_utils.py:109)."""
    g = group if group is not None else C.get_group(0)
    if g is None or g.nranks <= 1 or not C.in_spmd_region():
        return x
    _check_uniform(local_count, g.nranks, "global_gather")
    axes = g.axis_names
    squeeze = x.ndim == 2
    if squeeze:
        from ...ops import manipulation as M

        # inverse of global_scatter's 2D form: the SAME shape-preserving
        # block exchange (it is an involution). The previous
        # (split=1, concat=0) form on the [n, k, d] reshape was neither
        # the inverse nor generally legal (needed n | k).
        n = g.nranks
        x = M.reshape(x, [n, x.shape[0] // n, x.shape[1]])
        out = _a2a(x, axes, 0, 0, "global_gather")
        return M.reshape(out, [-1, out.shape[-1]])
    return _a2a(x, axes, 1, 0, "global_gather")
