"""paddle.distributed.stream namespace (reference:
python/paddle/distributed/communication/stream/ — the stream-variant
collectives taking sync_op/use_calc_stream and returning task handles).

TPU stance: XLA owns streams and ordering — every collective here is
issued into the one compiled/async PJRT stream, so the stream variants
delegate to the standard collectives and return the same completed-task
handles (`task.wait()` is a no-op barrier on an already-ordered op).
``use_calc_stream`` is accepted and ignored by design: there is no
separate comm stream to pick on TPU.
"""
from __future__ import annotations

from . import collective as _c

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "alltoall",
           "alltoall_single", "broadcast", "reduce", "scatter", "send",
           "recv"]


def _drop_stream_kw(kw):
    kw.pop("use_calc_stream", None)
    return kw


def all_reduce(tensor, op=None, group=None, sync_op=True, **kw):
    args = {} if op is None else {"op": op}
    return _c.all_reduce(tensor, group=group, sync_op=sync_op,
                         **args, **_drop_stream_kw(kw))


def all_gather(tensor_or_list, tensor, group=None, sync_op=True, **kw):
    return _c.all_gather(tensor_or_list, tensor, group=group,
                         sync_op=sync_op, **_drop_stream_kw(kw))


def reduce_scatter(tensor, tensor_or_list, op=None, group=None,
                   sync_op=True, **kw):
    args = {} if op is None else {"op": op}
    return _c.reduce_scatter(tensor, tensor_or_list, group=group,
                             sync_op=sync_op, **args,
                             **_drop_stream_kw(kw))


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True,
             **kw):
    from . import compat as _compat

    return _compat.alltoall(out_tensor_list, in_tensor_list,
                            group=group, sync_op=sync_op)


def alltoall_single(out_tensor, in_tensor, out_split_sizes=None,
                    in_split_sizes=None, group=None, sync_op=True, **kw):
    from . import compat as _compat

    return _compat.alltoall_single(out_tensor, in_tensor,
                                   in_split_sizes, out_split_sizes,
                                   group=group, sync_op=sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True, **kw):
    return _c.broadcast(tensor, src, group=group, sync_op=sync_op,
                        **_drop_stream_kw(kw))


def reduce(tensor, dst=0, op=None, group=None, sync_op=True, **kw):
    args = {} if op is None else {"op": op}
    return _c.reduce(tensor, dst, group=group, sync_op=sync_op, **args,
                     **_drop_stream_kw(kw))


def scatter(tensor, tensor_or_list=None, src=0, group=None,
            sync_op=True, **kw):
    return _c.scatter(tensor, tensor_or_list, src, group=group,
                      **_drop_stream_kw(kw))


def send(tensor, dst=0, group=None, sync_op=True, **kw):
    return _c.send(tensor, dst, group=group, sync_op=sync_op,
                   **_drop_stream_kw(kw))


def recv(tensor, src=0, group=None, sync_op=True, **kw):
    return _c.recv(tensor, src, group=group, sync_op=sync_op,
                   **_drop_stream_kw(kw))
