"""meta_parallel: the per-strategy model wrappers.

(reference: python/paddle/distributed/fleet/meta_parallel/ — model.py:32
``fleet.distributed_model`` picks the wrapper by active strategy:
pure-dp → DataParallel, mp → TensorParallel, pp → PipelineParallel.)
"""
from __future__ import annotations

from .parallel_layers import (LayerDesc, PipelineLayer, SegmentLayers,
                              SharedLayerDesc)
from .pipeline_parallel import PipelineParallel
from .tensor_parallel import SegmentParallel, TensorParallel

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer",
           "PipelineParallel", "TensorParallel", "SegmentParallel",
           "wrap_distributed_model"]


def wrap_distributed_model(model, hcg, strategy):
    """(reference fleet/model.py:132-160 decision ladder)"""
    if hcg is None:
        return model
    if hcg.get_pipe_parallel_world_size() > 1 or isinstance(model,
                                                            PipelineLayer):
        return PipelineParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy)
    if hcg.get_sep_parallel_world_size() > 1:
        return SegmentParallel(model, hcg, strategy)
    from ...parallel import DataParallel

    if hcg.get_data_parallel_world_size() > 1 or \
            hcg.get_sharding_parallel_world_size() > 1:
        return DataParallel(model)
    return model
