"""PipelineParallel — the train_batch driver for PipelineLayer models.

Reference surface: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py — ``PipelineParallel.train_batch`` (:689) driving the
1F1B schedule (forward_backward_pipeline :455) with Python-side NCCL p2p
per microbatch.

TPU-native: the whole schedule (all microbatches, forward AND backward,
plus the optimizer update) is ONE compiled XLA program built by
``ParallelEngine`` — the pipeline rotation lives inside the model's
``PipelineLayer._pipe_fn`` (lax.scan + ppermute), and its jax.vjp is the
reverse schedule. Host Python dispatches one executable per step instead
of 4·M p2p calls, which removes the per-microbatch launch overhead the
reference pays (SURVEY.md §7 hard parts: "1F1B under XLA"). The same
program expresses interleaved virtual stages (``pp_configs
["num_virtual_pipeline_stages"] > 1``) as a circular rotation — see the
pp_layers module docstring; this wrapper validates the microbatch-count
constraint that schedule adds (accumulate_steps % pp == 0).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ....core.enforce import enforce
from ....tensor import Tensor
from ...engine import ParallelEngine
from .parallel_layers.pp_layers import PipelineLayer
from .tensor_parallel import _DelegateWrapper

__all__ = ["PipelineParallel"]


def _unwrap_optimizer(opt):
    return getattr(opt, "_inner_opt", opt)


class PipelineParallel(_DelegateWrapper):
    def __init__(self, layers: PipelineLayer, hcg, strategy):
        enforce(isinstance(layers, PipelineLayer),
                "PipelineParallel expects a PipelineLayer model")
        super().__init__(layers, hcg, strategy)
        pconf = getattr(strategy, "pipeline_configs", {}) or {}
        self.accumulate_steps = int(pconf.get("accumulate_steps", 1))
        self.micro_batch_size = int(pconf.get("micro_batch_size", 0))
        self._engine: Optional[ParallelEngine] = None
        self._train_step = None
        self._eval_steps: Dict[bool, Any] = {}
        self.total_loss = None

    # -- engine plumbing -------------------------------------------------
    def _ensure_engine(self, optimizer):
        if self._engine is None:
            self._layers._num_microbatches = self.accumulate_steps
            self._engine = ParallelEngine(
                self._layers, _unwrap_optimizer(optimizer),
                self._hcg.mesh if self._hcg is not None else None)
        return self._engine

    def _check_batch(self, inputs):
        if self._hcg is None:
            return
        # circular-interleave feasibility, named by knob: microbatches
        # enter the ring in groups of pp_degree (pp_layers._pipe_fn)
        vpp = getattr(self._layers, "_vpp", 1)
        pp = self._hcg.get_pipe_parallel_world_size()
        if vpp > 1:
            enforce(self.accumulate_steps % pp == 0,
                    "pipeline_configs['accumulate_steps'] "
                    f"({self.accumulate_steps}) must be a multiple of "
                    f"pp_degree ({pp}) when pp_configs"
                    f"['num_virtual_pipeline_stages'] is {vpp}: the "
                    "circular schedule admits microbatches in groups of "
                    "pp_degree so each returning circuit slots into the "
                    "ring tick its carry arrives on")
        if self.micro_batch_size <= 0:
            return
        first = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
        data_deg = (self._hcg.get_data_parallel_world_size()
                    * self._hcg.get_sharding_parallel_world_size())
        want = self.micro_batch_size * self.accumulate_steps * data_deg
        enforce(first.shape[0] == want,
                f"global batch {first.shape[0]} != micro_batch_size "
                f"{self.micro_batch_size} x accumulate_steps "
                f"{self.accumulate_steps} x data degree {data_deg}")

    # -- reference API ---------------------------------------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One full pipeline step: data = [inputs, labels].

        (reference pipeline_parallel.py:689 — here fwd+bwd over all
        microbatches plus the optimizer step execute as one XLA program.)
        """
        inputs, labels = data
        self._check_batch(inputs)
        if lr_scheduler is not None:
            # the engine advances the optimizer's attached schedule once
            # per step — attach the caller's so it is the one advanced
            _unwrap_optimizer(optimizer).set_lr_scheduler(lr_scheduler)
        eng = self._ensure_engine(optimizer)
        if self._train_step is None:
            def fn(model, batch):
                return model.compute_loss(batch["inputs"], batch["labels"])

            # the scaler of the FIRST call is baked into the compiled
            # step (the traced dynamic loss-scaling protocol)
            self._train_step = eng.train_step(fn, scaler=scaler)
        return self._train_step({"inputs": inputs, "labels": labels})

    # -- crash-consistent checkpointing ---------------------------------
    def save_checkpoint(self, path=None, **kw):
        """Checkpoint the compiled pipeline's full training state
        (ParallelEngine.save_checkpoint): params incl. the pp x vpp
        stacked chunks shard-exact, ZeRO-scattered moments, AMP
        state, counters, RNG."""
        enforce(self._engine is not None,
                "run train_batch once before save_checkpoint (the "
                "engine owns the optimizer state being saved)")
        return self._engine.save_checkpoint(path, **kw)

    def restore_checkpoint(self, path, optimizer=None, scaler=None):
        """Restore from a committed checkpoint, resharding to the
        current topology. Callable before the first train_batch when
        ``optimizer`` is given (the engine is built here so moments
        have shaped, sharded targets to land in)."""
        if self._engine is None:
            enforce(optimizer is not None,
                    "restore_checkpoint before the first train_batch "
                    "needs the optimizer (it owns the moment targets)")
            self._ensure_engine(optimizer)
        return self._engine.restore_checkpoint(path, scaler=scaler)

    def profile_exposed_comm(self, data, repeats: int = 3,
                             publish: bool = True):
        """Exposed-comm attribution of the compiled pipeline step
        (ParallelEngine.profile_exposed_comm): per-axis overlapped-vs-
        exposed comm split + the grad_sync_exposed_seconds gauge.
        Offline — run between steps; engine state is restored."""
        inputs, labels = data
        enforce(self._train_step is not None,
                "run train_batch once before profile_exposed_comm "
                "(the compiled step and its comm ledger must exist)")
        return self._engine.profile_exposed_comm(
            self._train_step, {"inputs": inputs, "labels": labels},
            repeats=repeats, publish=publish)

    def eval_batch(self, data, compute_loss: bool = True):
        inputs, labels = data
        eng = self._engine
        enforce(eng is not None, "call train_batch once before eval_batch "
                "(or use forward directly)")
        if compute_loss not in self._eval_steps:
            from jax.sharding import PartitionSpec as P

            from ... import collective as C

            axes = tuple(a for a in eng.mesh.axis_names
                         if eng.mesh.shape[a] > 1)

            def fn(model, batch, _loss=compute_loss):
                if _loss:
                    loss = model.compute_loss(batch["inputs"],
                                              batch["labels"])
                    v = C.t_pmean(loss._value, axes) if axes else loss._value
                    return Tensor(v, stop_gradient=True)
                return model(batch["inputs"])

            self._eval_steps[compute_loss] = (
                eng.eval_step(fn), P() if compute_loss else None)
        step, out_spec = self._eval_steps[compute_loss]
        return step({"inputs": inputs, "labels": labels}, out_spec=out_spec)
