"""TensorParallel wrapper (reference:
python/paddle/distributed/fleet/meta_parallel/tensor_parallel.py — wraps
the model and broadcasts mp params within the mp group so every rank
starts from identical weights).

TPU-native: parameters are single-controller global jax.Arrays, so they
are consistent across ranks by construction; the wrapper is API surface
(strategy bookkeeping + forward delegation)."""
from __future__ import annotations

from ....nn.layer import Layer

__all__ = ["TensorParallel", "SegmentParallel", "_DelegateWrapper"]


class _DelegateWrapper(Layer):
    def __init__(self, layers: Layer, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers: bool = True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix: str = "", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)


class TensorParallel(_DelegateWrapper):
    def __init__(self, layers: Layer, hcg=None, strategy=None):
        super().__init__(layers, hcg, strategy)
        # model-parallel setup plumb: a strategy handed straight to the
        # wrapper (no fleet.init) must still drive the mp_configs knobs
        # the mpu layers read live (collective_matmul.overlap_enabled)
        from .. import _fleet_state

        if strategy is not None and _fleet_state.get("strategy") is None:
            _fleet_state["strategy"] = strategy


class SegmentParallel(_DelegateWrapper):
    """(reference meta_parallel/segment_parallel.py:26 — sep axis wrapper)"""
    pass
