"""Pipeline-parallel model partitioning — TPU-native PipelineLayer.

Reference surface: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py — ``LayerDesc`` (:56), ``SharedLayerDesc``,
``SegmentLayers`` (:92), ``PipelineLayer`` (:261). There, each pp rank
builds ONLY its stage's layers and microbatches flow between ranks via
NCCL p2p driven from Python (pp_utils/p2p_communication.py).

TPU-native redesign: every rank traces the SAME program (SPMD). The
homogeneous middle run of the layer list (the transformer blocks) is
stored as *stacked* parameters with a leading layer axis sharded over the
'pp' mesh axis — each pp rank physically holds L/pp layers. The schedule
is a ``lax.scan`` over pipeline ticks with ``lax.ppermute`` rotating
activations stage→stage+1 over the ICI ring (see pipeline schedule in
``PipelineLayer._pipe_fn``); jax.vjp of that function IS the reverse
pipeline, so backward scheduling needs no hand-written p2p.

Memory (the 1F1B question): the reference's 1F1B
(meta_parallel/pipeline_parallel.py:455) exists to keep at most S
microbatches of activations alive instead of M. In a single compiled
SPMD program the fwd/bwd tick interleaving of 1F1B is not expressible
(jax.vjp replays backward after all of forward), so the same memory
property is achieved differently: each pipeline TICK is wrapped in
``jax.checkpoint`` (on by default, ``tick_checkpoint=False`` to
disable), so the only activations that survive the forward scan are the
O(microbatch) stage-boundary carries — per-block residuals exist for
just ONE tick at a time during backward. Cost: one extra stage-forward
per tick (the standard remat trade).

Interleaved virtual stages (the CIRCULAR schedule): contrary to the
folk claim that interleave presupposes 1F1B's hand-scheduled fwd/bwd
ticks, a GSPMD-style *circular* schedule expresses it inside the same
single ``lax.scan`` + ``lax.ppermute`` program. With
``num_virtual_pipeline_stages = vpp > 1`` each stage holds ``vpp``
NON-contiguous layer chunks of ``L/(pp*vpp)`` layers: the stacked
parameters are shaped ``[vpp, L/vpp, ...]`` with axis 1 sharded over
'pp', so rank ``s`` physically owns, for every circuit ``v``, the
global layers ``[v*L/vpp + s*K, v*L/vpp + (s+1)*K)`` (``K =
L/(pp*vpp)``) — the round-robin chunk→stage map of Megatron/GSPMD
interleave. Each microbatch makes ``vpp`` circuits of the ICI ring
(stage S-1's output ppermutes back into stage 0, which applies its
NEXT chunk to it), so the scan runs ``T = vpp*M + S - 1`` ticks of
``1/vpp``-sized stage work: bubble (S-1)/(vpp*M+S-1) instead of
(S-1)/(M+S-1) — the up-to-~2x small-M win measured in
PP_SCHEDULE.json (tools/pp_schedule_measure.py). Microbatches are
admitted in groups of S (circuit v+1 of a microbatch re-enters stage 0
exactly S ticks after circuit v left it — a pure shift register, no
carry buffering), which is why ``accumulate_steps % pp == 0`` is
required when vpp > 1. ``jax.vjp`` of the circular program IS the
exact reverse schedule, and ``tick_checkpoint`` remat keeps the
O(microbatch) memory property per chunk (each tick now recomputes only
K layers). RNG streams are distinct per (tick, stage, chunk) — see
``_tick_seed``.

Stage ownership: the prologue (embedding) runs under ``lax.cond`` only
on stage 0 and the epilogue (final norm + 50K-vocab head + loss) only
on the last stage — other ranks execute the zero branch, so the
redundant FLOPs are actually skipped at runtime, not just masked.
Gradient ownership falls out of ``lax.cond``'s vjp (non-owners
contribute zero cotangents) and the engine psums replicated-param
grads over 'pp' (tied word embeddings then work with no special
casing — stage-0 and last-stage contributions sum, which is what the
reference's SharedLayerDesc allreduce does by hand).
"""
from __future__ import annotations

from contextlib import nullcontext as _nullcontext
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..... import ops
from .... import collective as C
from .....autograd import engine as _engine
from .....autograd.engine import no_grad
from .....core import rng as _rng
from .....core.enforce import enforce
from .....nn.container import LayerList
from .....nn.layer import Layer
from .....observability import commledger as _cl
from .....tensor import Parameter, Tensor
from .... import collective as C

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    """Deferred layer construction (reference pp_layers.py:56)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer) if isinstance(layer_func, type) \
                else not callable(layer_func):
            raise TypeError("layer_func must be a Layer subclass or callable")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({getattr(self.layer_func, '__name__', self.layer_func)})"


class SharedLayerDesc(LayerDesc):
    """A layer whose parameters are shared across its occurrences
    (reference pp_layers.py SharedLayerDesc — embedding/head weight
    tying across first/last stage). Occurrences after the first reuse
    the built instance; ``forward_func`` overrides how it is applied."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layers into num_parts parts (reference pp_layers.py:92).

    method: "uniform" or "layer:<ClassName>" (cut so each part starts at
    an instance of the named class).

    With ``num_virtual_pipeline_stage = vpp > 1`` the layer list is cut
    into ``num_stages * vpp`` parts whose stage ASSIGNMENT is
    interleaved round-robin (part j → stage ``j % num_stages``, circuit
    ``j // num_stages``) — the circular-schedule chunk→stage map — NOT
    the reference's contiguous ``num_parts *= vpp`` blocks-per-stage
    pre-multiplication."""

    def __init__(self, layers_desc, num_parts, method="uniform",
                 num_virtual_pipeline_stage=None):
        self._layers_desc = layers_desc
        self.method = method
        self.num_stages = num_parts
        self.num_virtual = num_virtual_pipeline_stage or 1
        self.num_parts = num_parts * self.num_virtual
        self.num_items = len(layers_desc)
        enforce(self.num_items >= self.num_parts,
                f"layer number ({self.num_items}) should be no less than "
                f"the number of segments = pp degree ({self.num_stages}) "
                f"x num_virtual_pipeline_stages ({self.num_virtual}) = "
                f"{self.num_parts}")

    def part_stage(self, part_idx: int) -> int:
        """Physical pp stage owning segment ``part_idx``: interleaved
        round-robin under virtual stages (part j → stage j % pp during
        circuit j // pp), contiguous identity otherwise."""
        enforce(0 <= part_idx < self.num_parts,
                f"part {part_idx} out of range [0, {self.num_parts})")
        return part_idx % self.num_stages

    def part_chunk(self, part_idx: int) -> int:
        """Circuit (virtual-stage chunk) index of segment ``part_idx``
        on its owning stage."""
        enforce(0 <= part_idx < self.num_parts,
                f"part {part_idx} out of range [0, {self.num_parts})")
        return part_idx // self.num_stages

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(self.num_items, self.num_parts)
        if self.method.startswith("layer:"):
            cls_name = self.method.split(":", 1)[1]
            weights = [0] * len(self._layers_desc)
            for i, d in enumerate(self._layers_desc):
                fn = d.layer_func if isinstance(d, LayerDesc) else type(d)
                name = getattr(fn, "__name__", str(fn))
                if name == cls_name:
                    weights[i] = 1
            idxs = [i for i, w in enumerate(weights) if w]
            total = len(idxs)
            enforce(total % self.num_parts == 0,
                    f"the number of {cls_name} ({total}) must be divisible "
                    f"by pp degree ({self.num_stages}) x "
                    f"num_virtual_pipeline_stages ({self.num_virtual}) "
                    f"= {self.num_parts}")
            per = total // self.num_parts
            return ([0] + [idxs[k * per] for k in range(1, self.num_parts)]
                    + [self.num_items])
        raise ValueError(f"unknown seg_method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts) -> List[int]:
        result = [0]
        part = num_items // num_parts
        extra = num_items % num_parts
        for i in range(num_parts):
            result.append(result[-1] + part + (1 if i < extra else 0))
        return result


class _FuncLayer(Layer):
    """Wraps a bare callable desc entry as a (parameterless) Layer."""

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *a, **k):
        return self._fn(*a, **k)


class _SharedApply(Layer):
    """Later occurrence of a SharedLayerDesc: applies ``forward_func`` to
    the shared instance (does NOT own the parameters)."""

    def __init__(self, shared: Layer, forward_func):
        super().__init__()
        object.__setattr__(self, "_shared_ref", shared)  # not a sublayer
        self._forward_func = forward_func

    def forward(self, *a, **k):
        if self._forward_func is not None:
            return self._forward_func(self._shared_ref, *a, **k)
        return self._shared_ref(*a, **k)


def _bind(params: Sequence[Parameter], values):
    """Functional bind (same contract as distributed.engine.bind_params)."""
    from ....engine import bind_params

    return bind_params(params, values)


def _tick_seed(base_seed, t, stage, chunk):
    """Distinct rng stream per (tick, stage, chunk): dropout masks must
    differ across microbatches, stages, AND the vpp chunks a stage
    applies on different circuits of the same tick phase. Affine mix of
    odd/coprime constants over uint32; uniqueness over realistic
    (t, stage, chunk) grids is pinned by tests/test_pp_vpp.py."""
    return (base_seed * jnp.uint32(1000003)
            + t.astype(jnp.uint32) * jnp.uint32(2654435761)
            + stage.astype(jnp.uint32)
            + chunk.astype(jnp.uint32) * jnp.uint32(40503))


class PipelineLayer(Layer):
    """Pipeline-partitioned model (reference pp_layers.py:261).

    ``layers`` is a list of LayerDesc / SharedLayerDesc / Layer /
    callables. The longest homogeneous run of LayerDescs (the decoder
    blocks) becomes the pipelined middle; everything before/after is
    prologue/epilogue, replicated over pp ranks.
    """

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, recompute_ctx=None,
                 num_virtual_pipeline_stages: Optional[int] = None,
                 tick_checkpoint: bool = True):
        super().__init__()
        from ... import fleet as _fleet_pkg  # noqa: F401 (cycle guard)

        if num_stages is None:
            hcg = self._hcg()
            num_stages = (hcg.get_pipe_parallel_world_size()
                          if hcg is not None else 1)
        self._num_stages = int(num_stages)
        if num_virtual_pipeline_stages is None:
            # plumbed from strategy.hybrid_configs["pp_configs"] via
            # fleet.init -> HybridCommunicateGroup
            hcg = self._hcg()
            num_virtual_pipeline_stages = (
                hcg.get_virtual_pipeline_parallel_world_size()
                if hcg is not None else 1)
        vpp = int(num_virtual_pipeline_stages or 1)
        enforce(vpp >= 1,
                f"num_virtual_pipeline_stages must be >= 1; got {vpp}")
        if vpp > 1:
            enforce(self._num_stages > 1,
                    f"num_virtual_pipeline_stages={vpp} (the circular "
                    f"interleaved schedule) needs a pipelined mesh, but "
                    f"pp_degree is {self._num_stages} — set "
                    "hybrid_configs['pp_degree'] > 1 or drop "
                    "hybrid_configs['pp_configs']"
                    "['num_virtual_pipeline_stages']")
        self._vpp = vpp
        self._tick_checkpoint = bool(tick_checkpoint)
        self._loss_fn = loss_fn
        # the stacked blocks share ONE scanned body, so recompute is
        # all-or-nothing here: every block (interval=1) or none (0) —
        # a per-k-th-layer policy is not expressible inside lax.scan
        enforce(recompute_interval in (0, 1),
                "recompute_interval must be 0 (off) or 1 (recompute every "
                f"block); got {recompute_interval}")
        self._recompute_interval = recompute_interval
        self._seg_method = seg_method
        self._num_microbatches = 1
        self._descs = list(layers)
        # pipelined models use grad-ownership masking: the engine must
        # psum replicated-param grads over 'pp' (see module docstring)
        self._pp_ownership = True

        self._shared: Dict[str, Layer] = {}
        built: List[Layer] = []
        for d in self._descs:
            built.append(self._build_one(d))

        lo, hi = self._homogeneous_run(self._descs)
        mid = built[lo:hi]
        n_mid = len(mid)
        total = self._num_stages * self._vpp
        enforce(n_mid % total == 0 if total > 1 else True,
                f"pipelined middle has {n_mid} layers (L), not divisible "
                f"by pp_degree ({self._num_stages}) x "
                f"num_virtual_pipeline_stages ({self._vpp}) = {total}; "
                "each stage must own num_virtual_pipeline_stages chunks "
                f"of L/{total} layers — adjust num_layers or the "
                "pp_degree / num_virtual_pipeline_stages knobs")
        self.prologue = LayerList(built[:lo])
        self.epilogue = LayerList(built[hi:])
        self._n_blocks = n_mid

        # stack the middle blocks' params along a leading layer axis
        template = mid[0] if mid else None
        object.__setattr__(self, "_template", template)
        self._t_params: List[Parameter] = []
        self._s_params: List[Parameter] = []
        if template is not None:
            names = [n for n, _ in template.named_parameters()]
            per_block = [dict(b.named_parameters()) for b in mid]
            for n in names:
                tp = per_block[0][n]
                stacked = jnp.stack([pb[n]._value for pb in per_block])
                base = getattr(tp, "dist_attr", None)
                base = tuple(base) if isinstance(base, P) else \
                    (None,) * tp.ndim
                if self._vpp > 1:
                    # circular interleave: leading chunk axis laid out
                    # round-robin over stages — sharding axis 1 (L/vpp
                    # layer rows) over 'pp' hands rank s, for every
                    # circuit v, the non-contiguous global layers
                    # [v*L/vpp + s*K, v*L/vpp + (s+1)*K)
                    stacked = stacked.reshape(
                        (self._vpp, n_mid // self._vpp) + stacked.shape[1:])
                    sp = Parameter(stacked, trainable=tp.trainable)
                    sp.dist_attr = P(None, "pp", *base)
                    sp.is_distributed = True
                elif total > 1:
                    sp = Parameter(stacked, trainable=tp.trainable)
                    sp.dist_attr = P("pp", *base)
                    sp.is_distributed = True
                else:
                    sp = Parameter(stacked, trainable=tp.trainable)
                    if any(a is not None for a in base):
                        sp.dist_attr = P(None, *base)
                        sp.is_distributed = True
                self.add_parameter("blocks__" + n.replace(".", "__"), sp)
                self._t_params.append(tp)
                self._s_params.append(sp)
        # segment bookkeeping (reference parity: part boundaries, plus
        # the interleaved part→(stage, chunk) map under virtual stages)
        if mid:
            seg = SegmentLayers(
                self._descs[lo:hi], self._num_stages, seg_method,
                self._vpp if self._vpp > 1 else None)
            self.segment_parts = seg.do_segment()
            self.segment_part_stages = [seg.part_stage(j)
                                        for j in range(seg.num_parts)]
            self.segment_part_chunks = [seg.part_chunk(j)
                                        for j in range(seg.num_parts)]
        else:
            self.segment_parts = [0]
            self.segment_part_stages = []
            self.segment_part_chunks = []

    # -- construction helpers -------------------------------------------
    def _hcg(self):
        from ... import fleet as _fleet

        return _fleet.get_hybrid_communicate_group()

    def _build_one(self, d) -> Layer:
        if isinstance(d, SharedLayerDesc):
            if d.layer_name in self._shared:
                return _SharedApply(self._shared[d.layer_name],
                                    d.forward_func)
            inst = d.build_layer()
            self._shared[d.layer_name] = inst
            return inst
        if isinstance(d, LayerDesc):
            return d.build_layer()
        if isinstance(d, Layer):
            return d
        if callable(d):
            return _FuncLayer(d)
        raise TypeError(f"cannot build pipeline entry {d!r}")

    @staticmethod
    def _homogeneous_run(descs) -> tuple:
        """[lo, hi) of the longest run of plain LayerDescs with the same
        layer_func — the pipelineable middle."""
        best = (0, 0)
        i = 0
        n = len(descs)
        while i < n:
            d = descs[i]
            if type(d) is LayerDesc:
                j = i
                while j < n and type(descs[j]) is LayerDesc and \
                        descs[j].layer_func is d.layer_func:
                    j += 1
                if j - i > best[1] - best[0]:
                    best = (i, j)
                i = j
            else:
                i += 1
        return best

    # -- pure functions over stacked values ------------------------------
    def _block_apply(self, row_vals, x_val):
        """Apply the template block with its params bound to one stacked
        row. Pure in (row_vals, x_val) given the ambient rng seed."""
        with no_grad(), _bind(self._t_params, row_vals):
            out = self._template(Tensor(x_val, stop_gradient=True))
        if isinstance(out, tuple):
            out = out[0]
        return out._value

    def _apply_rows(self, x_val, stacked_vals, n_rows):
        """lax.scan over the stacked layer axis — program size stays O(1)
        in depth (40-layer stacks compile as one block body)."""
        if n_rows == 0:
            return x_val
        base_seed = _rng.traced_seed()
        block = self._block_apply
        if self._recompute_interval:
            block = jax.checkpoint(block)

        def body(x, xs):
            row, ridx = xs
            if base_seed is None:
                return block(list(row), x), None
            # distinct rng stream per layer row (dropout sites must not
            # share masks across the scanned layers)
            seed_j = base_seed * jnp.uint32(31) + ridx.astype(jnp.uint32)
            with _rng.fork_traced(seed_j):
                return block(list(row), x), None

        xs = (tuple(stacked_vals), jnp.arange(n_rows))
        out, _ = lax.scan(body, x_val, xs)
        return out

    def _pp_axes(self):
        hcg = self._hcg()
        if hcg is None:
            return None
        g = hcg.get_pipe_parallel_group()
        if g is None or not g.axis_names or g.nranks <= 1:
            return None
        return g.axis_names

    def _pipe_fn(self, M, base_seed, pp_axes):
        """The pipeline schedule: microbatch rotation over the pp ring.

        Returns pure fn(x, *stacked) -> last-stage outputs (valid rows
        only on the last pp stage; zeros-masked elsewhere).

        vpp=1 (GPipe-family): T = M + S - 1 ticks; at tick t, stage s
        computes microbatch t - s; lax.ppermute rotates activations one
        stage forward per tick on ICI.

        vpp>1 (circular interleave): each stage holds vpp chunks of
        K = L/(S*vpp) layers (round-robin layout, see __init__); every
        activation makes vpp circuits of the ring before emitting, so
        the scan runs T = vpp*M + S - 1 ticks of 1/vpp-sized stage work
        — bubble (S-1)/(vpp*M+S-1). Work items (microbatch m, circuit
        v) enter stage 0 in groups of S microbatches, all circuits of a
        group before the next group (entry order e = g*S*vpp + v*S +
        (m - g*S)): circuit v+1 of an item re-enters stage 0 exactly S
        ticks after circuit v entered, which is precisely when its
        carry returns from stage S-1 — a pure shift register, no
        buffering, hence the accumulate_steps % pp == 0 requirement.
        The item at stage s on tick t is e = t - s; its chunk is
        v = (e mod S*vpp) // S.

        jax.vjp of this function yields the exact reverse schedule
        (backward pipeline) automatically — for vpp>1 included, because
        the circular rotation is ordinary data flow through scan +
        ppermute.
        """
        enforce(len(pp_axes) == 1, "pp must map to a single mesh axis")
        axis = pp_axes[0]
        V = self._vpp

        def fn(x_val, *stacked_vals):
            S = C.axis_size(axis)
            enforce(S == self._num_stages,
                    f"model was built for {self._num_stages} pipeline "
                    f"stages but the mesh '{axis}' axis has {S} — build "
                    "the PipelineLayer after fleet.init (or pass "
                    "num_stages)")
            if V > 1:
                enforce(M % S == 0,
                        f"accumulate_steps (microbatches M={M}) must be "
                        f"a multiple of pp_degree (S={S}) when "
                        f"num_virtual_pipeline_stages={V}: the circular "
                        "schedule admits microbatches in groups of "
                        "pp_degree so returning circuits slot into the "
                        "ring without buffering")
            stage = lax.axis_index(axis)
            B = x_val.shape[0]
            enforce(B % M == 0, f"local batch {B} not divisible by "
                    f"microbatches {M}")
            mb = B // M
            xm = x_val.reshape((M, mb) + x_val.shape[1:])
            if stacked_vals:
                n_rows = stacked_vals[0].shape[1 if V > 1 else 0]
            else:
                n_rows = 0
            carry = jnp.zeros((mb,) + x_val.shape[1:], x_val.dtype)
            out_buf = jnp.zeros_like(xm)
            perm = [(i, (i + 1) % self._num_stages)
                    for i in range(self._num_stages)]
            SV = S * V
            E = V * M          # total work items (microbatch, circuit)

            def tick(x_in, seed_t, v, *sv):
                if V > 1:
                    # chunk selection INSIDE the remat boundary: the
                    # backward recomputes the [K, ...] gather instead
                    # of saving a per-tick copy of the chunk params
                    # (T x param bytes — the memory-flatness test
                    # catches the difference)
                    sv = tuple(
                        lax.dynamic_index_in_dim(s_, v, 0, keepdims=False)
                        for s_ in sv)
                with _rng.fork_traced(seed_t):
                    return self._apply_rows(x_in, sv, n_rows)

            if self._tick_checkpoint:
                # memory-honest schedule: only the O(microbatch) stage
                # boundary carries survive the forward scan; the blocks'
                # residuals exist for one tick at a time during backward
                # (recomputed), so activation memory does NOT scale with
                # microbatch count (see module docstring). Under vpp>1
                # each tick rematerializes only its K-layer chunk.
                tick = jax.checkpoint(tick)

            def body(state, t):
                carry, out_buf = state
                # work item at this stage this tick: entry index e,
                # chunk v = (e mod S*vpp) // S, microbatch
                # m = (e // S*vpp)*S + (e mod S*vpp) mod S
                e = jnp.clip(t - stage, 0, E - 1)
                r = e % SV
                v = r // S
                m_in = jnp.clip((e // SV) * S + r, 0, M - 1)
                x_mb = lax.dynamic_index_in_dim(xm, m_in, 0,
                                                keepdims=False)
                # stage 0 injects a fresh microbatch on circuit 0; on
                # later circuits it consumes the carry returning from
                # stage S-1 (the circular rotation)
                x_in = jnp.where((stage == 0) & (v == 0), x_mb, carry)
                seed_t = _tick_seed(base_seed, t, stage, v)
                y = tick(x_in, seed_t, v, *stacked_vals)
                # the last stage emits items on their FINAL circuit only
                ew = t - (S - 1)
                ewc = jnp.clip(ew, 0, E - 1)
                rw = ewc % SV
                idx = jnp.clip((ewc // SV) * S + (rw - S * (V - 1)),
                               0, M - 1)
                write = ((stage == S - 1) & (ew >= 0) & (ew < E)
                         & (rw >= S * (V - 1)))
                cur = lax.dynamic_index_in_dim(out_buf, idx, 0,
                                               keepdims=False)
                out_buf = lax.dynamic_update_index_in_dim(
                    out_buf, jnp.where(write, y, cur), idx, 0)
                carry = C.t_ppermute(y, axis, perm)
                return (carry, out_buf), None

            # the ring ppermute in `body` is traced ONCE but executes
            # E + S - 1 times per forward; noting it under scan_trips
            # makes the comm ledger trips-exact for the pipeline axis
            # (observability/commledger.py — AD synthesizes the reverse
            # ring as the ppermute transpose without re-entering the
            # noting shim, so only the forward schedule is recorded)
            with _cl.scan_trips(E + S - 1):
                (carry, out_buf), _ = lax.scan(
                    body, (carry, out_buf), jnp.arange(E + S - 1))
            return out_buf.reshape(x_val.shape)

        return fn

    # -- forward ---------------------------------------------------------
    def _run_seq(self, layers, x):
        for lyr in layers:
            if isinstance(x, tuple):
                x = lyr(*x)
            else:
                x = lyr(x)
        return x

    def _middle(self, x: Tensor) -> Tensor:
        if self._n_blocks == 0:
            return x
        pp_axes = self._pp_axes() if C.in_spmd_region() else None
        stacked = self._s_params
        svals = [p._value for p in stacked]
        seed = _rng.traced_seed()
        if seed is None:
            seed = jnp.uint32(np.random.randint(0, 2**31))
        if pp_axes is None:
            n_blocks = self._n_blocks
            vpp = self._vpp

            def fn(xv, *sv):
                if vpp > 1:
                    # chunked layout [vpp, L/vpp, ...] flattens back to
                    # global layer order for sequential application
                    sv = [s.reshape((n_blocks,) + s.shape[2:])
                          for s in sv]
                with _rng.fork_traced(seed):
                    return self._apply_rows(xv, sv, n_blocks)
        else:
            fn = self._pipe_fn(self._num_microbatches, seed, pp_axes)

        if _engine.is_grad_enabled() and (not x.stop_gradient or
                                          any(p.trainable for p in stacked)):
            out_val, vjp_fn = jax.vjp(fn, x._value, *svals)
            out = Tensor(out_val, stop_gradient=False)
            _engine.record_custom("pipeline_middle", lambda g: vjp_fn(g),
                                  [x] + list(stacked), [out], out_val)
        else:
            out = Tensor(fn(x._value, *svals), stop_gradient=True)
        return out

    # -- stage-owned prologue/epilogue -----------------------------------
    @staticmethod
    def _reachable_params(layers, extra=()) -> List[Parameter]:
        """Params the given layers (incl. shared-instance references and
        e.g. a parameterized loss Layer in ``extra``) can touch — the
        bind/vjp set for one _owned_apply call."""
        seen: Dict[int, Parameter] = {}
        def add(lyr):
            for p in lyr.parameters():
                seen.setdefault(id(p), p)
            ref = getattr(lyr, "_shared_ref", None)
            if ref is not None:
                add(ref)
        for lyr in list(layers) + [e for e in extra if isinstance(e, Layer)]:
            add(lyr)
        return list(seen.values())

    def _owned_apply(self, fn_eager, inputs: List[Tensor], owner: int,
                     pp_axes, own: Optional[List[Parameter]] = None
                     ) -> Tensor:
        """Run ``fn_eager(*inputs)`` only on pp stage ``owner`` via
        ``lax.cond`` — the other stages execute the zero branch, so the
        FLOPs (e.g. the 50K-vocab head) are actually skipped at
        runtime. ``lax.cond``'s vjp hands non-owners zero cotangents,
        which is exactly the grad-ownership masking the engine's 'pp'
        psum expects. ``own`` scopes the bind/vjp set to the params the
        callee can actually reach (no zero-cotangent churn for the
        other stage's params)."""
        if own is None:
            sid = {id(p) for p in self._s_params}
            own = [p for p in self.parameters() if id(p) not in sid]
        in_vals = [t._value for t in inputs]
        pvals = [p._value for p in own]
        axes = tuple(pp_axes)
        amb_seed = _rng.traced_seed()

        def pure(iv, pv):
            # fork an owner-distinct rng stream for the duration of the
            # call: without it, dropout inside the prologue/epilogue
            # splits the ambient traced key under jax.eval_shape's /
            # lax.cond's inner trace and leaks that tracer into the
            # global rng state (UnexpectedTracerError on the next use)
            ctx = (_rng.fork_traced(
                amb_seed * jnp.uint32(48271) + jnp.uint32(owner + 1))
                if amb_seed is not None else _nullcontext())
            with ctx, no_grad(), _bind(own, pv):
                out = fn_eager(*[Tensor(v, stop_gradient=True)
                                 for v in iv])
            return out._value

        out_sd = jax.eval_shape(pure, in_vals, pvals)

        def fn(iv, pv):
            stage = C.axis_index(axes)
            return lax.cond(
                stage == owner,
                lambda ops_: pure(*ops_),
                lambda ops_: jnp.zeros(out_sd.shape, out_sd.dtype),
                (iv, pv))

        needs_grad = _engine.is_grad_enabled() and (
            any(not t.stop_gradient for t in inputs)
            or any(p.trainable for p in own))
        if needs_grad:
            out_val, vjp_fn = jax.vjp(fn, in_vals, pvals)
            out = Tensor(out_val, stop_gradient=False)

            def bwd(g):
                div, dpv = vjp_fn(g)
                return list(div) + list(dpv)

            _engine.record_custom("pp_owned", bwd, list(inputs) + own,
                                  [out], out_val)
        else:
            out = Tensor(fn(in_vals, pvals), stop_gradient=True)
        return out

    def _pp_trunk(self, ins, pp_axes) -> Tensor:
        """Stage-0-owned prologue + pipelined middle (shared by
        forward/compute_loss under pp). Output rows are valid on the
        last stage only."""
        if len(self.prologue):
            x = self._owned_apply(
                lambda *ts: self._run_seq(
                    self.prologue, ts if len(ts) > 1 else ts[0]),
                list(ins), 0, pp_axes,
                own=self._reachable_params(self.prologue))
        else:
            x = ins[0]
        return self._middle(x)

    def forward(self, *args):
        pp_axes = self._pp_axes() if C.in_spmd_region() else None
        if pp_axes is None:
            x = self._run_seq(self.prologue,
                              args if len(args) > 1 else args[0])
            enforce(isinstance(x, Tensor),
                    "the pipelined middle takes a single Tensor")
            x = self._middle(x)
            return self._run_seq(self.epilogue, x)

        S = self._num_stages
        x = self._pp_trunk(args, pp_axes)
        if len(self.epilogue):
            out = self._owned_apply(
                lambda t: self._run_seq(self.epilogue, t), [x], S - 1,
                pp_axes, own=self._reachable_params(self.epilogue))
        else:
            out = x
        return _pp_collect(out, pp_axes, S - 1)

    def compute_loss(self, inputs, labels) -> Tensor:
        """forward + loss_fn; under pp the epilogue AND the loss run
        only on the last stage (lax.cond) and the scalar is broadcast."""
        enforce(self._loss_fn is not None,
                "PipelineLayer needs loss_fn for train_batch")
        ins = inputs if isinstance(inputs, (tuple, list)) else (inputs,)
        lbs = list(labels) if isinstance(labels, (tuple, list)) else [labels]
        pp_axes = self._pp_axes() if C.in_spmd_region() else None
        if pp_axes is None:
            out = self.forward(*ins)
            return self._loss_fn(out, *lbs)

        S = self._num_stages
        x = self._pp_trunk(ins, pp_axes)

        def tail(t, *lb):
            return self._loss_fn(self._run_seq(self.epilogue, t), *lb)

        loss = self._owned_apply(
            tail, [x] + lbs, S - 1, pp_axes,
            own=self._reachable_params(self.epilogue,
                                       extra=(self._loss_fn,)))
        return _pp_collect(loss, pp_axes, S - 1)

    def grad_bucket_seam(self):
        """The stacked-params chunk seam for layer-grained gradient
        bucketing (distributed/grad_buckets.py): ``[(param, k)]`` where
        the first ``k`` dims of each stacked parameter enumerate layer
        rows — 1 for the plain ``[L/pp, ...]`` stack, 2 for the circular
        interleave's ``[vpp, L/(pp*vpp), ...]`` chunk layout. The engine
        cuts these rows into size-targeted buckets and runs the grad
        reduce-scatter / pmean as a scan over them, so the per-bucket
        collective can overlap the neighboring buckets' work instead of
        waiting for the whole stacked grad."""
        k = 2 if self._vpp > 1 else 1
        return [(p, k) for p in self._s_params if p.trainable]

    # reference API parity helpers
    def get_num_stages(self) -> int:
        return self._num_stages

    def get_num_virtual_stages(self) -> int:
        """Chunks per stage in the circular interleaved schedule (1 =
        plain GPipe-family rotation)."""
        return self._vpp

    @property
    def parameters_in_stacked_blocks(self):
        return list(self._s_params)


# -- pp ownership / collect custom ops ----------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _pp_collect_raw(x, axes, src):
    stage = C.axis_index(axes)
    return C.t_psum(jnp.where(stage == src, x,
                             jnp.zeros((), x.dtype)), axes)


_pp_collect_raw.defvjp(
    lambda x, axes, src: (_pp_collect_raw(x, axes, src), None),
    lambda axes, src, _, g: (jnp.where(C.axis_index(axes) == src, g,
                                       jnp.zeros((), g.dtype)),))


def _pp_collect(x: Tensor, axes, src) -> Tensor:
    """Broadcast the last stage's tensor to all pp ranks; cotangent is
    masked to the source stage (gradient ownership)."""
    val = _pp_collect_raw(x._value, tuple(axes), src)
    out = Tensor(val, stop_gradient=x.stop_gradient)
    if _engine.is_grad_enabled() and not x.stop_gradient:
        out.stop_gradient = False

        def bwd(g):
            return (jnp.where(C.axis_index(tuple(axes)) == src, g,
                              jnp.zeros((), g.dtype)),)

        _engine.record_custom("pp_collect", bwd, [x], [out], val)
    return out

