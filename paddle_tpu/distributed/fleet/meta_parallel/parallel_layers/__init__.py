from .pp_layers import (LayerDesc, PipelineLayer, SegmentLayers,
                        SharedLayerDesc)

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]
