"""Role makers (reference: python/paddle/distributed/fleet/base/
role_maker.py — PaddleCloudRoleMaker:654, UserDefinedRoleMaker:1163).

TPU stance: roles come from the launcher environment
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER, the same
variables distributed/launch/main.py sets); the parameter-server role
split (servers/heter workers) is a PS-era concept the SPMD runtime does
not have — every process is a collective worker. The classes exist so
reference code `fleet.init(role_maker=PaddleCloudRoleMaker(
is_collective=True))` runs unchanged.
"""
from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    def __init__(self, is_collective: bool = True, **kwargs):
        self._is_collective = bool(is_collective)

    def _worker_index(self) -> int:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    def _worker_num(self) -> int:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    # -- reference API surface -----------------------------------------
    def worker_index(self) -> int:
        return self._worker_index()

    def worker_num(self) -> int:
        return self._worker_num()

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False  # no parameter servers in the SPMD runtime

    def is_first_worker(self) -> bool:
        return self._worker_index() == 0

    def role_id(self) -> int:
        return self._worker_index()

    def worker_endpoints(self, to_string=False):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        lst = [e for e in eps.split(",") if e]
        return ",".join(lst) if to_string else lst

    def server_endpoints(self, to_string=False):
        return "" if to_string else []


class PaddleCloudRoleMaker(RoleMakerBase):
    """Roles from the launcher environment (reference role_maker.py:654
    reads the same PADDLE_* variables)."""


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicit role assignment (reference role_maker.py:1163): takes
    current_id / role / worker_num and overrides the environment."""

    def __init__(self, is_collective: bool = True, current_id: int = 0,
                 role=Role.WORKER, worker_num: int = 1,
                 server_endpoints=None, **kwargs):
        super().__init__(is_collective=is_collective)
        self._current_id = int(current_id)
        self._role = role
        self._num = int(worker_num)

    def _worker_index(self) -> int:
        return self._current_id

    def _worker_num(self) -> int:
        return self._num


class UtilBase:
    """fleet.util (reference: fleet/base/util_factory.py UtilBase) —
    host-side helpers over the TCPStore collectives."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from ... import runtime as _rt

        vals = _rt.all_gather_object_host(np.asarray(input))
        stacked = np.stack([np.asarray(v) for v in vals])
        if mode == "sum":
            return stacked.sum(axis=0)
        if mode == "max":
            return stacked.max(axis=0)
        if mode == "min":
            return stacked.min(axis=0)
        raise ValueError(f"all_reduce mode {mode!r} not in sum/max/min")

    def all_gather(self, input, comm_world="worker"):
        from ... import runtime as _rt

        return _rt.all_gather_object_host(input)

    def barrier(self, comm_world="worker"):
        from ... import runtime as _rt

        _rt.host_barrier("fleet_util_barrier")

    def get_file_shard(self, files):
        """Split a file list contiguously across workers (reference
        util_factory.get_file_shard: first len%n workers get one
        extra)."""
        import os

        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        base, extra = divmod(len(files), n)
        start = rank * base + min(rank, extra)
        return list(files[start:start + base + (1 if rank < extra else 0)])

    def print_on_rank(self, message, rank_id=0):
        import os

        if int(os.environ.get("PADDLE_TRAINER_ID", "0")) == int(rank_id):
            print(message)
