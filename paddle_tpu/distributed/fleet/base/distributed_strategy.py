"""DistributedStrategy (reference:
python/paddle/distributed/fleet/base/distributed_strategy.py — protobuf-
backed config; hybrid_configs at :1808. Plain-python here, same keys.)
"""
from __future__ import annotations

from typing import Any, Dict

__all__ = ["DistributedStrategy"]

_HYBRID_DEFAULTS = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    # expert parallelism: stacked [E, d, h] MoE expert weights shard
    # over the 'ep' mesh axis and token dispatch/combine is an
    # all_to_all on it (incubate/.../moe/moe_layer.py). Like dp, 'ep'
    # splits the token batch — the engine treats it as a data axis.
    "ep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "ep", "mp"],
    # mp_async_allreduce (reference hybrid_configs:1808): overlap the
    # TP/SP collectives with the matmuls they feed via the chunked ring
    # decompositions in distributed/collective_matmul.py
    "mp_configs": {"mp_async_allreduce": False},
    # num_virtual_pipeline_stages (vpp): circular interleaved pipeline
    # schedule — each pp stage holds vpp non-contiguous layer chunks and
    # activations make vpp circuits of the ICI ring, shrinking the
    # bubble to (S-1)/(vpp*M+S-1) (meta_parallel/parallel_layers/
    # pp_layers.py). Requires num_layers % (pp*vpp) == 0 and
    # accumulate_steps % pp == 0.
    "pp_configs": {"num_virtual_pipeline_stages": 1},
    # ep_async_dispatch: fuse the MoE dispatch/combine all_to_alls with
    # the expert GEMMs as a chunked ppermute ring
    # (distributed/collective_matmul.py moe_a2a_ffn) so the ICI
    # exchange hides behind the per-chunk expert FFN; unfused fallback
    # outside SPMD or when E doesn't chunk over the ring.
    "moe_configs": {"ep_async_dispatch": False},
    # comm_overlap (reference sharding_configs surface): T3-style
    # bucketed backward grad sync — the stage-2 reduce-scatter / DP
    # grad all-reduce issues per layer-grained bucket (the pp stacked-
    # params seam for pipelined models, size-targeted param_spec groups
    # for flat ones) instead of one exposed end-of-backward tail;
    # comm_buffer_size_MB targets the per-bucket payload
    # (distributed/grad_buckets.py). Bit-exact loss/param parity vs
    # the unbucketed path.
    # sharding_stage (reference group_sharded levels os/os_g/p_g_os):
    # 1/2 shard optimizer state (and scatter grads) over 'sharding';
    # 3 additionally stores PARAMETERS shard-only (dim-0 scattered over
    # the sharding group, engine._ZeroPlan store_sharded) and
    # all-gathers them just-in-time at forward entry — per signature
    # bucket when comm_overlap's plan exists (the T3 mirror of the
    # backward reduce-scatter; the pp stacked-params seam gathers as a
    # lax.scan with scan_trips-exact ledger bytes), per parameter
    # otherwise. stage3_release_after_forward picks the gather grain:
    # True (default) = the bucketed just-in-time schedule, each
    # bucket's full image an independent XLA temp released after its
    # last (backward) use; False = one per-parameter gather wave at
    # step entry, the whole image alive across the step (the stage-2
    # style schedule, fewer/larger nodes). Both are bit-exact data
    # movement — loss/params match stage 2 and each other.
    "sharding_configs": {"comm_overlap": False,
                         "comm_buffer_size_MB": 25.0,
                         "sharding_stage": 2,
                         "stage3_release_after_forward": True},
    # quant_comm: int8 (or fp8 e4m3) wire compression for the grad
    # reduce-scatter/pmean buckets (grad_sync — rides comm_overlap's
    # bucket plan, with a per-bucket error-feedback residual carried as
    # training state) and the collective-matmul ring ticks (mp_rings).
    # Per-chunk symmetric scales over a fixed `chunk` lattice with a
    # bf16 scale sidecar; dtype "none" = full-precision wire
    # (bit-identical to the pre-knob behavior). See
    # distributed/quant_comm.py.
    # param_gather additionally ships the ZeRO stage-2/3 param
    # all-gather quantized with each rank's OWN shard spliced back
    # exactly (no error accumulation in the authoritative state).
    "quant_comm": {"dtype": "none", "grad_sync": True, "mp_rings": True,
                   "param_gather": True, "chunk": 256,
                   "error_feedback": True,
                   "stochastic_rounding": False},
}


class _SubConfig(dict):
    __getattr__ = dict.get

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        self._hybrid_configs: Dict[str, Any] = dict(_HYBRID_DEFAULTS)
        # nested sub-configs must not alias the class-level defaults
        for k in ("mp_configs", "pp_configs", "moe_configs",
                  "sharding_configs", "quant_comm"):
            self._hybrid_configs[k] = _SubConfig(_HYBRID_DEFAULTS[k])
        self.pipeline_configs: Dict[str, Any] = {
            "micro_batch_size": 1, "accumulate_steps": 1}
        self.amp = False
        self.amp_configs: Dict[str, Any] = {}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {}
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {}
        self.lamb = False
        self.dgc = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {}
        self.gradient_scale_configs: Dict[str, Any] = {"scale_strategy": "avg"}

    @property
    def hybrid_configs(self):
        return self._hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, configs: Dict[str, Any]):
        for k, v in configs.items():
            if k in ("mp_configs", "pp_configs", "moe_configs",
                     "sharding_configs", "quant_comm") \
                    and isinstance(v, dict):
                merged = _SubConfig(self._hybrid_configs.get(k, {}))
                merged.update(v)
                self._hybrid_configs[k] = merged
            else:
                self._hybrid_configs[k] = v

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self._hybrid_configs})"
