"""Hybrid-parallel topology over the TPU mesh.

(reference: python/paddle/distributed/fleet/base/topology.py:178
CommunicateTopology + HybridCommunicateGroup, axis order
["dp", "pp", "sharding", "sep", "mp"], per-axis comm groups created via
paddle.distributed.new_group at topology.py:208-233.)

TPU-native: the topology IS a jax.sharding.Mesh whose named axes are the
parallelism dimensions. Each comm "group" is just the axis name —
collectives on it lower to XLA collectives over ICI. Axis order maps the
innermost (fastest-varying, physically-adjacent chips) axis to 'mp',
exactly like the reference puts mp innermost for NVLink locality.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax

from ... import collective as C

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_DEFAULT_ORDER = ["dp", "pp", "sharding", "sep", "ep", "mp"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names: List[str] = None,
                 dims: List[int] = None, order: List[str] = None):
        self._parallel_names = hybrid_group_names or _DEFAULT_ORDER
        self._dims = dims or [1] * len(self._parallel_names)
        self._order = order or self._parallel_names

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))


class HybridCommunicateGroup:
    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp_degree: int = 1, mp_degree: int = 1, pp_degree: int = 1,
                 sharding_degree: int = 1, sep_degree: int = 1,
                 order: Optional[List[str]] = None,
                 devices: Optional[list] = None,
                 vpp_degree: int = 1, ep_degree: int = 1):
        if topology is not None:
            degrees = {n: topology.get_dim(n)
                       for n in topology.get_hybrid_group_names()}
            dp_degree = degrees.get("dp", 1)
            mp_degree = degrees.get("mp", 1)
            pp_degree = degrees.get("pp", 1)
            sharding_degree = degrees.get("sharding", 1)
            sep_degree = degrees.get("sep", 1)
            ep_degree = degrees.get("ep", 1)
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sep_degree = sep_degree
        self._ep_degree = ep_degree
        # virtual pipeline (circular interleave) chunks per pp stage —
        # a schedule knob, not a mesh axis: it multiplies layer chunks,
        # not devices (pp_layers.PipelineLayer reads it at build time)
        self._vpp_degree = int(vpp_degree or 1)
        self._order = list(order) if order else list(_DEFAULT_ORDER)
        if ep_degree > 1 and "ep" not in self._order:
            raise ValueError(
                f"ep_degree={ep_degree} needs an 'ep' axis in the hybrid "
                f"order, got {self._order}; add 'ep' (default order is "
                f"{_DEFAULT_ORDER}) or drop the custom order")
        self._topo = topology or CommunicateTopology(
            self._order, [self._degree_of(n) for n in self._order])

        total = (dp_degree * mp_degree * pp_degree * sharding_degree *
                 sep_degree * ep_degree)
        devs = devices if devices is not None else jax.devices()
        if total > len(devs):
            raise ValueError(
                f"hybrid degrees product {total} > visible devices "
                f"{len(devs)}")
        shape = tuple(self._degree_of(n) for n in self._order)
        mesh_devs = np.array(devs[:total]).reshape(shape)
        self.mesh = jax.sharding.Mesh(mesh_devs, tuple(self._order))
        C.init_parallel_env(self.mesh)

        self._groups: Dict[str, C.Group] = {}
        for name in self._order:
            self._groups[name] = C.new_group(
                axis_names=(name,), nranks=self._degree_of(name), name=name)
        # dp+sharding fused group for grad sync in sharding mode
        self._groups["dp_sharding"] = C.new_group(
            axis_names=("dp", "sharding"),
            nranks=dp_degree * sharding_degree, name="dp_sharding")
        self._groups["world"] = C.get_group(0)

    def _degree_of(self, name: str) -> int:
        return {"dp": self._dp_degree, "mp": self._mp_degree,
                "pp": self._pp_degree, "sharding": self._sharding_degree,
                "sep": self._sep_degree, "ep": self._ep_degree}[name]

    # -- degrees (reference API parity) ---------------------------------
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_virtual_pipeline_parallel_world_size(self):
        """num_virtual_pipeline_stages from pp_configs (1 = no
        interleave); consumed by PipelineLayer at build time."""
        return self._vpp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    # -- ranks: traced inside SPMD region -------------------------------
    def get_data_parallel_rank(self):
        return self._axis_rank("dp")

    def get_model_parallel_rank(self):
        return self._axis_rank("mp")

    def get_stage_id(self):
        return self._axis_rank("pp")

    def get_sharding_parallel_rank(self):
        return self._axis_rank("sharding")

    def get_sep_parallel_rank(self):
        return self._axis_rank("sep")

    def get_expert_parallel_rank(self):
        return self._axis_rank("ep")

    def _axis_rank(self, name):
        if C.in_spmd_region():
            from jax import lax

            return lax.axis_index(name)
        return 0

    # -- groups ---------------------------------------------------------
    def get_data_parallel_group(self):
        return self._groups["dp"]

    def get_model_parallel_group(self):
        return self._groups["mp"]

    def get_pipe_parallel_group(self):
        return self._groups["pp"]

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sep_parallel_group(self):
        return self._groups["sep"]

    def get_expert_parallel_group(self):
        return self._groups.get("ep")

    def get_check_parallel_group(self, *a):
        return self._groups["world"]

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    # -- pipeline helpers ------------------------------------------------
    def is_first_stage(self):
        return self.get_stage_id() == 0 if not C.in_spmd_region() else None

    def is_last_stage(self):
        return (self.get_stage_id() == self._pp_degree - 1
                if not C.in_spmd_region() else None)

    @property
    def topology(self):
        return self._topo

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id

    def __repr__(self):
        return (f"HCG(dp={self._dp_degree}, pp={self._pp_degree}, "
                f"sharding={self._sharding_degree}, sep={self._sep_degree}, "
                f"ep={self._ep_degree}, mp={self._mp_degree}, "
                f"order={self._order})")
