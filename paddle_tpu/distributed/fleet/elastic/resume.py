"""Elastic recovery: checkpoint/resume train state across world changes.

(reference: python/paddle/distributed/fleet/elastic/manager.py:237-264 —
on scale in/out the manager signals the launcher, which restarts the
job with the new world; training resumes from the last checkpoint.)

TPU-native flow: a live jax runtime cannot resize, so recovery is
restart-shaped by design —

1. every rank periodically checkpoints (the atomic sharded distributed
   checkpoint: each process writes only its addressable shards, the
   commit protocol guarantees a crash mid-save can never be read back —
   see checkpoint/save_state_dict.py);
2. the :class:`ElasticManager` heartbeat watcher detects the world
   change (or the watchdog detects a hung collective); survivors stop
   stepping, dump a flight record, and exit with
   :data:`RESTART_EXIT_CODE` for the launcher — the
   :func:`train_with_recovery` loop wires all three signals;
3. the relaunched job — ANY new world size/mesh — calls
   :func:`resume_latest`: the NEWEST COMMITTED checkpoint is found by a
   pure directory scan (uncommitted/corrupt dirs are skipped by
   construction), reshard-on-load reassembles each tensor's addressable
   windows from the old layout's shards, the optimizer moments
   included, and training continues from the recorded step.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ....observability import goodput as _gp
from ...checkpoint import (latest_committed, load_state_dict,
                           read_extra_meta, resolve_committed,
                           save_state_dict)

__all__ = ["save_train_state", "load_train_state", "resume_latest",
           "train_with_recovery", "opt_state_tensors",
           "RESTART_EXIT_CODE"]

# the exit code a survivor returns so the launcher relaunches instead of
# declaring the job failed (reference: elastic manager's restart signal)
RESTART_EXIT_CODE = 3


def opt_state_tensors(model, optimizer):
    """Optimizer state (array slots + master weights) as checkpoint
    tensors keyed by the MODEL's structured parameter names.

    Auto-generated parameter names (``linear_7.w_0``) come from a
    process-global counter, so a rebuilt model in the same process gets
    DIFFERENT names and a p.name-keyed checkpoint silently fails to
    fill its moments. Structured names (``layers.3.fc1.weight``) are a
    function of the module tree alone — stable across rebuilds,
    processes, and topologies.

    Returns ``(slots, tensors)``: ``slots[key] = (param, slot_name)``
    for writing loaded values back, ``tensors[key] = Tensor(value)``
    as the save source / load target.
    """
    from ....tensor import Tensor

    name_of = {id(p): n for n, p in model.named_parameters()}
    slots: Dict[str, Any] = {}
    tensors: Dict[str, Any] = {}
    for i, p in enumerate(optimizer._parameter_list or []):
        name = name_of.get(id(p)) or p.name or f"param_{i}"
        st = optimizer._states.get(id(p)) or {}
        for k, v in st.items():
            if not hasattr(v, "shape"):
                continue
            slots[f"{name}.{k}"] = (p, k)
            tensors[f"{name}.{k}"] = Tensor(v)
        mw = optimizer._master_weights.get(id(p))
        if mw is not None:
            slots[f"{name}.master_weight"] = (p, "master_weight")
            tensors[f"{name}.master_weight"] = Tensor(mw)
    return slots, tensors


def _apply_opt_state(optimizer, slots, tensors) -> None:
    """Write loaded checkpoint tensors back into the optimizer."""
    import jax.numpy as jnp

    for key, (p, k) in slots.items():
        v = tensors[key]._value
        if k == "master_weight":
            optimizer._master_weights[id(p)] = v.astype(jnp.float32)
        else:
            optimizer._states[id(p)][k] = v


def save_train_state(path: str, model, optimizer=None, step: int = 0,
                     extra: Optional[Dict[str, Any]] = None,
                     async_save: bool = False) -> None:
    """Sharded save of model (+ optimizer moments) + scalar metadata.

    The metadata commits atomically WITH the shards (inside the tmp →
    COMMIT → rename unit), so a crash can never leave tensors from one
    save next to counters from another."""
    from ....optimizer.lr import LRScheduler

    state = {"model": model.state_dict()}
    meta: Dict[str, Any] = {"step": int(step)}
    if optimizer is not None:
        meta["opt_step_count"] = int(optimizer._step_count)
        if isinstance(optimizer._lr, LRScheduler):
            meta["lr_scheduler"] = optimizer._lr.state_dict()
        _, tensors = opt_state_tensors(model, optimizer)
        if tensors:
            state["optim"] = tensors
    if extra:
        meta.update(extra)
    save_state_dict(state, path, async_save=async_save, extra_meta=meta)


def load_train_state(path: str, model, optimizer=None) -> Dict[str, Any]:
    """Fill model/optimizer from the checkpoint, resharding to the NEW
    world's layout; returns the metadata (incl. ``step``)."""
    from ....core.enforce import enforce

    resolved = resolve_committed(path)
    enforce(resolved is not None,
            f"no committed checkpoint at {path!r} (resume_latest(base) "
            "falls back to the newest committed one)")
    meta = read_extra_meta(resolved)
    with _gp.segment("restore"):
        # phase 1: model params FIRST — any optimizer state
        # materialized below (fresh multi-precision masters) must copy
        # the LOADED weights, never the pre-load random init
        model_t = {"model": model.state_dict()}
        load_state_dict(model_t, resolved)
        model.set_state_dict(model_t["model"])
        if optimizer is None:
            return meta

        from ....optimizer.lr import LRScheduler

        # moments not materialized yet (fresh optimizer): allocate
        # them so the load has shaped targets to fill (AFTER the param
        # load above — fresh multi-precision masters must copy the
        # LOADED weights)
        shapes = optimizer._state_shapes()
        if shapes:
            for p in optimizer._parameter_list:
                optimizer._param_state(p, shapes)
        slots, tensors = opt_state_tensors(model, optimizer)
        if tensors:
            load_state_dict({"optim": tensors}, resolved)
            _apply_opt_state(optimizer, slots, tensors)
        optimizer._step_count = int(meta.get("opt_step_count",
                                             meta["step"]))
        if "lr_scheduler" in meta and isinstance(optimizer._lr,
                                                 LRScheduler):
            optimizer._lr.set_state_dict(meta["lr_scheduler"])
    return meta


def resume_latest(base: str, model, optimizer=None
                  ) -> Optional[Dict[str, Any]]:
    """Restore from the NEWEST COMMITTED checkpoint under ``base`` (a
    CheckpointManager base dir); None when no committed checkpoint
    exists (cold start). Corrupt/uncommitted dirs are skipped by the
    commit-marker scan; a checkpoint that turns out corrupt mid-load
    raises CheckpointCorruptError — delete it and call again to fall
    back one more save."""
    # continue the run's goodput journal FIRST: a journal left behind
    # by a killed process gets its dangling tail closed as the
    # recovery_restart segment the moment the relaunch scans for a
    # checkpoint — before any restore work books its own segment
    try:
        _gp.attach_dir(base)
    except OSError:
        pass            # unwritable base surfaces on the load below
    path = latest_committed(base)
    if path is None:
        return None
    meta = load_train_state(path, model, optimizer)
    meta.setdefault("checkpoint_path", path)
    return meta


def train_with_recovery(step_fn: Callable[[int], Any], total_steps: int,
                        *, start_step: int = 0,
                        save_fn: Optional[Callable[[int], None]] = None,
                        save_every: int = 0, elastic=None, watchdog=None,
                        on_step: Optional[Callable[[int, Any],
                                                   None]] = None
                        ) -> Tuple[str, int]:
    """Survivor-driven recovery loop around a compiled step function.

    Runs ``step_fn(step)`` for ``start_step <= step < total_steps``,
    checkpointing via ``save_fn(step+1)`` every ``save_every`` steps,
    and stops the moment either recovery signal fires:

    - ``elastic`` (an :class:`ElasticManager`): ``restart_needed``
      between steps (a peer's heartbeat aged out, or the manager hit
      ERROR on a dead store) — the world changed under us;
    - ``watchdog`` (a :class:`~paddle_tpu.distributed.watchdog.
      CommTaskManager`): the step is tracked against its timeout, so a
      hung collective (dead peer mid-step) raises instead of wedging.

    On a signal: a stall flight record is dumped (post-mortem), pending
    async checkpoint writes are NOT waited on (the store may be the
    thing that died — the commit protocol makes the half-written save
    harmless), and ``("restart", step)`` is returned so the caller can
    ``sys.exit(RESTART_EXIT_CODE)`` for the launcher to relaunch; the
    relaunched job resumes via :func:`resume_latest`. Completing every
    step returns ``("completed", total_steps)``.
    """
    from ...watchdog import TimeoutError_

    for step in range(start_step, total_steps):
        if elastic is not None and elastic.restart_needed:
            _dump_flight(f"elastic: world changed before step {step} "
                         f"(status {elastic.status.name})")
            _gp.note_event("restart_signal", step=step,
                           reason="elastic_world_change")
            return ("restart", step)
        try:
            if watchdog is not None:
                with watchdog.track(f"step{step}"):
                    out = step_fn(step)
                    jax.block_until_ready(jax.tree_util.tree_map(
                        lambda t: getattr(t, "_value", t), out))
            else:
                out = step_fn(step)
        except TimeoutError_:
            # the watchdog already dumped the flight record on its way up
            _gp.note_event("restart_signal", step=step,
                           reason="watchdog_timeout")
            return ("restart", step)
        if on_step is not None:
            on_step(step, out)
        if save_fn is not None and save_every > 0 \
                and (step + 1) % save_every == 0:
            save_fn(step + 1)
    return ("completed", total_steps)


def _dump_flight(reason: str) -> None:
    try:
        from ....observability import flight as _flight

        _flight.dump(reason=reason)
    except Exception:
        pass            # the post-mortem must never mask the recovery
