"""Elastic recovery: checkpoint/resume train state across world changes.

(reference: python/paddle/distributed/fleet/elastic/manager.py:237-264 —
on scale in/out the manager signals the launcher, which restarts the
job with the new world; training resumes from the last checkpoint.)

TPU-native flow: a live jax runtime cannot resize, so recovery is
restart-shaped by design —

1. every rank periodically calls :func:`save_train_state` (the sharded
   distributed checkpoint: each process writes only its addressable
   shards, see checkpoint/save_state_dict.py);
2. the :class:`ElasticManager` heartbeat watcher detects the world
   change; survivors stop stepping (``wait_restart``) and exit with a
   restart code for the launcher;
3. the relaunched job — ANY new world size/mesh — calls
   :func:`load_train_state`: reshard-on-load reassembles each tensor's
   addressable windows from the old layout's shards, the optimizer
   moments included, and training continues from the recorded step.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax

from ...checkpoint import load_state_dict, save_state_dict

__all__ = ["save_train_state", "load_train_state"]

_META = "train_meta.json"


def save_train_state(path: str, model, optimizer=None, step: int = 0,
                     extra: Optional[Dict[str, Any]] = None) -> None:
    """Sharded save of model (+ optimizer moments) + scalar metadata."""
    state = {"model": model.state_dict()}
    meta: Dict[str, Any] = {"step": int(step)}
    if optimizer is not None:
        osd = optimizer.state_dict()
        meta["opt_step_count"] = int(osd.pop("step_count", 0))
        lrs = osd.pop("LR_Scheduler", None)
        if lrs is not None:
            meta["lr_scheduler"] = lrs
        state["optim"] = osd
    if extra:
        meta.update(extra)
    save_state_dict(state, path)
    if jax.process_index() == 0:
        with open(os.path.join(path, _META), "w") as f:
            json.dump(meta, f)


def load_train_state(path: str, model, optimizer=None) -> Dict[str, Any]:
    """Fill model/optimizer from the checkpoint, resharding to the NEW
    world's layout; returns the metadata (incl. ``step``)."""
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    # phase 1: model params FIRST — any optimizer state materialized
    # below (fresh multi-precision masters) must copy the LOADED
    # weights, never the pre-load random init
    model_t = {"model": model.state_dict()}
    load_state_dict(model_t, path)
    model.set_state_dict(model_t["model"])
    if optimizer is None:
        return meta

    osd = optimizer.state_dict()
    osd.pop("step_count", None)
    osd.pop("LR_Scheduler", None)
    if not osd:
        # moments not materialized yet (fresh optimizer): allocate them
        # so the load has shaped targets to fill
        shapes = optimizer._state_shapes()
        if shapes:
            for p in optimizer._parameter_list:
                optimizer._param_state(p, shapes)
            osd = optimizer.state_dict()
            osd.pop("step_count", None)
            osd.pop("LR_Scheduler", None)
    if osd:
        targets = {"optim": osd}
        load_state_dict(targets, path)
        filled = dict(targets["optim"])
    else:
        filled = {}
    filled["step_count"] = meta.get("opt_step_count", meta["step"])
    if "lr_scheduler" in meta:
        filled["LR_Scheduler"] = meta["lr_scheduler"]
    optimizer.set_state_dict(filled)
    return meta
