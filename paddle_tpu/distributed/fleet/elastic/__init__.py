from .manager import ElasticManager, ElasticStatus  # noqa: F401
from .resume import (load_train_state, save_train_state,  # noqa: F401
                     resume_latest, train_with_recovery,
                     RESTART_EXIT_CODE)

__all__ = ["ElasticManager", "ElasticStatus", "save_train_state",
           "load_train_state", "resume_latest", "train_with_recovery",
           "RESTART_EXIT_CODE"]
