from .manager import ElasticManager, ElasticStatus  # noqa: F401

__all__ = ["ElasticManager", "ElasticStatus"]
