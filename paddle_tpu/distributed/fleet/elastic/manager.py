"""Elastic training manager.

(reference: python/paddle/distributed/fleet/elastic/manager.py:126 —
ElasticManager registers nodes in etcd with TTL leases, watches for
scale in/out, and signals the launcher to restart the job with the new
world. The etcd dependency is replaced by the native TCPStore
(csrc/tcp_store.cpp): heartbeats are timestamped keys, the watcher
thread ages them.)
"""
from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable, List, Optional

__all__ = ["ElasticManager", "ElasticStatus"]

logger = logging.getLogger("paddle_tpu.elastic")


class ElasticStatus(enum.Enum):
    COMPLETED = 0
    ERROR = 1
    HOLD = 2
    RESTART = 3
    EXIT = 4


class ElasticManager:
    """Node registry + heartbeat watcher over a TCPStore.

    Each node writes ``/elastic/<job>/nodes/<rank>`` = timestamp every
    ``heartbeat_interval``; the watcher marks the world changed when a
    node's heartbeat ages past ``node_timeout`` (scale-in) or a new rank
    appears (scale-out) and invokes ``on_world_change(alive_ranks)``.
    """

    def __init__(self, store, job_id: str = "default", rank: int = 0,
                 np_: int = 1, heartbeat_interval: float = 1.0,
                 node_timeout: float = 5.0,
                 on_world_change: Optional[Callable] = None):
        self.store = store
        self.job = job_id
        self.rank = rank
        self.np = np_
        self.heartbeat_interval = heartbeat_interval
        self.node_timeout = node_timeout
        self.on_world_change = on_world_change
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._last_world: Optional[tuple] = None
        # guards status: written by the heartbeat/watcher threads and
        # the driver (register/ack/exit) concurrently
        self._state_lock = threading.Lock()
        self.status = ElasticStatus.HOLD

    def _set_status(self, status: "ElasticStatus") -> None:
        with self._state_lock:
            self.status = status

    # -- registration / heartbeat --------------------------------------
    def _node_key(self, rank: int) -> str:
        return f"/elastic/{self.job}/nodes/{rank}"

    def register(self):
        self.store.set(self._node_key(self.rank), str(time.time()))
        t = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t.start()
        self._threads.append(t)
        w = threading.Thread(target=self._watch_loop, daemon=True)
        w.start()
        self._threads.append(w)
        self._set_status(ElasticStatus.HOLD)

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self.store.set(self._node_key(self.rank),
                               str(time.time()))
            except Exception as e:
                # a dead store means THIS node now looks dead to every
                # peer while still running — surface it loudly (status
                # ERROR flips restart_needed) instead of silently
                # letting the pod split-brain
                if not self._stop.is_set():
                    self._set_status(ElasticStatus.ERROR)
                    logger.error(
                        "elastic heartbeat for rank %d failed (%s: %s); "
                        "peers will see this node as dead — flagging "
                        "ERROR for the recovery loop", self.rank,
                        type(e).__name__, e)
                return

    # -- watching -------------------------------------------------------
    def alive_ranks(self) -> List[int]:
        now = time.time()
        alive = []
        for r in range(self.np):
            try:
                if not self.store.check(self._node_key(r)):
                    continue
                # short timeout: the key may vanish between check and get
                ts = float(self.store.get(self._node_key(r), timeout=0.2))
            except Exception:
                continue
            if now - ts <= self.node_timeout:
                alive.append(r)
        return alive

    def _watch_loop(self):
        while not self._stop.wait(self.heartbeat_interval):
            world = tuple(self.alive_ranks())
            if self._last_world is None:
                self._last_world = world
                continue
            if world != self._last_world:
                logger.warning("elastic world changed: %s -> %s",
                               self._last_world, world)
                self._last_world = world
                self._set_status(ElasticStatus.RESTART)
                if self.on_world_change:
                    self.on_world_change(list(world))

    @property
    def restart_needed(self) -> bool:
        """True when recovery must run: a peer changed the world
        (RESTART) or this node's own heartbeat died (ERROR — peers
        already consider us gone)."""
        with self._state_lock:
            return self.status in (ElasticStatus.RESTART,
                                   ElasticStatus.ERROR)

    def ack_world_change(self):
        """Acknowledge a handled RESTART so the manager is reusable
        (e.g. the driver decided the new world is acceptable and
        continues instead of relaunching); the watcher keeps comparing
        against the latest world. ERROR is sticky — a node whose own
        heartbeat died cannot talk itself back to health."""
        with self._state_lock:
            # atomic check-and-set: a concurrent watcher ERROR between
            # the read and the write must not be overwritten to HOLD
            if self.status == ElasticStatus.RESTART:
                self.status = ElasticStatus.HOLD

    def wait_restart(self, timeout: float = 60.0) -> bool:
        """Block until the watcher flags a world change (survivor-side
        recovery gate: stop stepping, checkpoint is already on disk,
        exit for the launcher to relaunch — see resume.py)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.restart_needed:
                return True
            time.sleep(self.heartbeat_interval / 2)
        return False

    def wait_world(self, n: int, timeout: float = 60.0) -> bool:
        """Block until ``n`` live ranks are registered (job start gate —
        the reference's pod-ready barrier)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.alive_ranks()) >= n:
                return True
            time.sleep(self.heartbeat_interval / 2)
        return False

    def exit(self, completed: bool = True):
        self._set_status(ElasticStatus.COMPLETED if completed
                         else ElasticStatus.ERROR)
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        try:
            self.store.delete_key(self._node_key(self.rank))
        except Exception:
            pass
