"""Fleet: the hybrid-parallel user API.

(reference: python/paddle/distributed/fleet/fleet.py:167 fleet.init →
_init_hybrid_parallel_env at fleet.py:603; model.py:32 distributed_model;
HybridParallelOptimizer in meta_optimizers/dygraph_optimizer/.)
"""
from __future__ import annotations

from typing import Optional

from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup
from . import layers  # noqa: F401
from . import utils  # noqa: F401
from .utils.recompute import recompute  # noqa: F401

__all__ = ["init", "DistributedStrategy", "HybridCommunicateGroup",
           "CommunicateTopology", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer", "fleet"]

_fleet_state = {"initialized": False, "hcg": None, "strategy": None}


from .base.role_maker import (PaddleCloudRoleMaker,  # noqa: F401
                              Role, RoleMakerBase, UserDefinedRoleMaker,
                              UtilBase)

util = UtilBase()  # fleet.util (reference: fleet.util property)


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """fleet.init analog: builds the hybrid mesh + HCG from
    strategy.hybrid_configs (reference fleet.py:603
    _init_hybrid_parallel_env)."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    pp_conf = hc.get("pp_configs", {}) or {}
    hcg = HybridCommunicateGroup(
        dp_degree=hc.get("dp_degree", 1), mp_degree=hc.get("mp_degree", 1),
        pp_degree=hc.get("pp_degree", 1),
        sharding_degree=hc.get("sharding_degree", 1),
        sep_degree=hc.get("sep_degree", 1),
        # expert parallelism: the 'ep' mesh axis MoELayer shards its
        # stacked expert weights over and runs token dispatch/combine on
        ep_degree=hc.get("ep_degree", 1),
        order=list(hc.get("order",
                          ["dp", "pp", "sharding", "sep", "ep", "mp"])),
        # circular-interleave schedule knob, plumbed to PipelineLayer
        # (pp_layers.py) via the HCG
        vpp_degree=pp_conf.get("num_virtual_pipeline_stages", 1))
    _fleet_state["initialized"] = True
    _fleet_state["hcg"] = hcg
    _fleet_state["strategy"] = strategy
    # seed the hybrid RNG tracker (local/global dropout streams) once —
    # WITHOUT touching the global stream (paddle.seed set by the user
    # before fleet.init must keep governing weight init)
    from .layers.mpu.random import GLOBAL_SEED, LOCAL_SEED, \
        get_rng_state_tracker

    tracker = get_rng_state_tracker()
    if LOCAL_SEED not in tracker.states_:
        seed = hc.get("mp_seed", 2024)
        if GLOBAL_SEED not in tracker.states_:
            tracker.add(GLOBAL_SEED, seed)
        tracker.add(LOCAL_SEED, seed + 2718)
    return hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _fleet_state["hcg"]


def get_strategy() -> Optional[DistributedStrategy]:
    return _fleet_state["strategy"]


def is_initialized() -> bool:
    return _fleet_state["initialized"]


def distributed_model(model):
    """(reference: fleet/model.py:32,132-160 — wraps by active strategy:
    pure-dp → DataParallel; pp → PipelineParallel; tp → TensorParallel.)"""
    from .meta_parallel import wrap_distributed_model

    return wrap_distributed_model(model, _fleet_state["hcg"],
                                  _fleet_state["strategy"])


def distributed_optimizer(optimizer, strategy=None):
    from .meta_optimizers import HybridParallelOptimizer

    return HybridParallelOptimizer(optimizer, _fleet_state["hcg"],
                                   strategy or _fleet_state["strategy"])


class _FleetNamespace:
    """Allows `from paddle_tpu.distributed import fleet; fleet.init(...)`
    plus attribute-style access used by reference code."""

    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    get_hybrid_communicate_group = staticmethod(get_hybrid_communicate_group)

    @property
    def worker_num(self):
        from .. import collective as C

        return C.get_world_size()


fleet = _FleetNamespace()
