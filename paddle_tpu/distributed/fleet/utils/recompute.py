"""Activation recomputation (gradient checkpointing).

TPU-native re-design of the reference's RecomputeFunction
(reference: python/paddle/distributed/fleet/recompute/recompute.py:108,404
— a PyLayer that stashes RNG state, drops activations, and re-runs the
forward inside backward; hybrid variant recompute_hybrid.py).

Here the block is wrapped in ``jax.checkpoint`` (remat): XLA drops the
block's internal activations and re-emits its forward into the backward
computation — the compiler-native version of re-running under a fresh
tape. RNG consistency is automatic: the rematerialized subgraph is the
*same traced program* (same PRNG key derivations), so dropout masks match
without the reference's CUDA RNG state-tracker dance (mpu/random.py:34).
"""
from __future__ import annotations

from typing import Any

import jax

from ....autograd import engine as _engine
from ....nn.layer import Layer
from ....tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def _collect_params(function):
    """Find the trainable params ``function`` will touch.

    Covers a Layer, a bound method of a Layer, and — the common reference
    idiom — a closure (``lambda h: self.mlp(h)``): closure cells holding
    Layers or Tensors are scanned so their params still receive grads.
    """
    seen, params = set(), []

    def add_layer(layer):
        if id(layer) in seen:
            return
        seen.add(id(layer))
        for p in layer.parameters():
            if not p.stop_gradient and id(p) not in seen:
                seen.add(id(p))
                params.append(p)

    if isinstance(function, Layer):
        add_layer(function)
        return params
    owner = getattr(function, "__self__", None)
    if isinstance(owner, Layer):
        add_layer(owner)
    for cell in getattr(function, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if isinstance(v, Layer):
            add_layer(v)
        elif isinstance(v, Tensor) and not v.stop_gradient \
                and id(v) not in seen:
            seen.add(id(v))
            params.append(v)
    return params


def recompute(function, *args, use_reentrant: bool = True,
              preserve_rng_state: bool = True, **kwargs):
    """Run ``function(*args, **kwargs)`` without keeping its internal
    activations; they are rematerialized during backward.

    ``function`` is typically a sublayer (or bound method of one) — its
    parameters are discovered so their gradients flow. Free functions of
    the inputs work too.
    """
    params = _collect_params(function)

    flat_in, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    t_idx = [i for i, v in enumerate(flat_in) if isinstance(v, Tensor)]
    t_args = [flat_in[i] for i in t_idx]

    need_grad = _engine.is_grad_enabled() and (
        any(not t.stop_gradient for t in t_args) or bool(params))
    if not need_grad:
        return function(*args, **kwargs)

    from ...engine import bind_params

    def _pure(pvals, avals):
        leaves = list(flat_in)
        for i, v in zip(t_idx, avals):
            leaves[i] = Tensor(v, stop_gradient=True)
        a, kw = jax.tree_util.tree_unflatten(treedef, leaves)
        with bind_params(params, pvals), _engine.no_grad():
            out = function(*a, **kw)
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out

    ckpt = jax.checkpoint(_pure)
    pvals = tuple(p._value for p in params)
    avals = tuple(t._value for t in t_args)
    out_vals, vjp_fn = jax.vjp(ckpt, pvals, avals)

    multi = isinstance(out_vals, tuple)
    outs = [Tensor(v, stop_gradient=False)
            for v in (out_vals if multi else (out_vals,))]

    def bwd(*gouts):
        g = gouts if multi else gouts[0]
        pgrads, agrads = vjp_fn(g)
        return tuple(pgrads) + tuple(agrads)

    _engine.record_custom("recompute", bwd, list(params) + t_args, outs,
                          out_vals if multi else (out_vals,))
    return tuple(outs) if multi else outs[0]


def recompute_sequential(ctx: dict, functions, *args, **kwargs):
    """Recompute a ``nn.Sequential`` in segments
    (reference: fleet/recompute/recompute_sequential.py)."""
    segments = int(ctx.get("segments", 1)) if ctx else 1
    layers = list(functions)
    if segments <= 1:
        return recompute(_Seq(layers), *args, **kwargs)
    size = max(1, len(layers) // segments)
    out = args
    for start in range(0, len(layers), size):
        seg = _Seq(layers[start:start + size])
        out = recompute(seg, *(out if isinstance(out, tuple) else (out,)),
                        **kwargs)
        kwargs = {}
    return out


class _Seq(Layer):
    def __init__(self, layers):
        super().__init__()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)
        self._layers_list = layers

    def forward(self, *x):
        for l in self._layers_list:
            x = l(*x) if isinstance(x, tuple) else l(x)
        return x
