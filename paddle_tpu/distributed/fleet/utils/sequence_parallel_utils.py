"""Megatron-style sequence parallelism over the mp mesh axis.

TPU-native re-design of the reference's SP utilities
(reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
:85-340 — ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers +
ColumnSequenceParallelLinear/RowSequenceParallelLinear;
register_sequence_parallel_allreduce_hooks:192).

SP keeps activations sharded along the *sequence* dim between the TP
linears: the column linear all-gathers the sequence right before its
matmul (backward: reduce-scatter), and the row linear reduce-scatters its
output along the sequence (backward: all-gather) — replacing the
identity/allreduce pair of plain TP with an allgather/reduce-scatter pair
of the same total bytes but sqrt(mp) lower peak activation memory.

Here every primitive is an XLA collective on the 'mp' axis inside the
SPMD region (shard_map), so XLA overlaps them with the matmuls on ICI.
Outside an SPMD region all primitives are identities (single-card parity,
the reference test strategy).

Layout note: the reference fixes seq as dim 0 ([s, b, h]); here the
sequence axis is a parameter (default 0 for reference parity) since the
native models use [b, s, h].
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ... import collective as C
from ....autograd import engine as _engine
from ....core.enforce import enforce
from ....framework.param_attr import ParamAttr
from ....nn import functional as F
from ....nn.layer import Layer
from ....tensor import Tensor
from ..layers.mpu.mp_ops import mp_active, mp_axes

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather", "reduce_scatter",
    "identity_in_sequence_parallel",
    "mark_as_sequence_parallel_parameter",
    "is_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
]


# the custom-vjp collective pairings and the tape-recording helper are
# shared with the TP primitives (mp_ops.py) — SP only changes which dim
# is gathered/scattered
from ..layers.mpu.mp_ops import (
    _custom, allgather_reducescatter_bwd as _allgather_rs_bwd,
    allgather_slice_bwd as _allgather_slice_bwd,
    reducescatter_allgather_bwd as _rs_allgather_bwd,
    slice_allgather_bwd as _slice_allgather_bwd)


# -- tensor-level SP ops (reference PyLayers) -----------------------------

def scatter(x: Tensor, group=None, axis: int = 0) -> Tensor:
    """Split the sequence dim across mp; backward all-gathers
    (reference ScatterOp, sequence_parallel_utils.py:85)."""
    if not mp_active(group):
        return x
    axes = mp_axes(group)
    enforce(x.shape[axis] % C.get_world_size(_group(group)) == 0,
            f"sequence dim {x.shape[axis]} must divide mp degree")

    def bwd(g):
        return (C.t_all_gather(g, axes, axis=axis, tiled=True),)

    return _custom("sp_scatter", _slice_allgather_bwd(x._value, axes, axis),
                   bwd, x)


def all_gather(x: Tensor, group=None, axis: int = 0) -> Tensor:
    """All-gather the sequence dim; backward reduce-scatters
    (reference AllGatherOp:150)."""
    if not mp_active(group):
        return x
    axes = mp_axes(group)

    def bwd(g):
        out = g
        for a in axes:
            out = C.t_psum_scatter(out, a, scatter_dimension=axis,
                                   tiled=True)
        return (out,)

    return _custom("sp_all_gather", _allgather_rs_bwd(x._value, axes, axis),
                   bwd, x)


def gather(x: Tensor, group=None, axis: int = 0) -> Tensor:
    """All-gather the sequence dim; backward takes the local slice
    (reference GatherOp:117)."""
    if not mp_active(group):
        return x
    axes = mp_axes(group)
    local = x._value.shape[axis]

    def bwd(g):
        idx = C.axis_index(axes)
        return (lax.dynamic_slice_in_dim(g, idx * local, local, axis=axis),)

    return _custom("sp_gather", _allgather_slice_bwd(x._value, axes, axis),
                   bwd, x)


def reduce_scatter(x: Tensor, group=None, axis: int = 0) -> Tensor:
    """Reduce-scatter (sum) along the sequence dim; backward all-gathers
    (reference ReduceScatterOp:180)."""
    if not mp_active(group):
        return x
    axes = mp_axes(group)

    def bwd(g):
        return (C.t_all_gather(g, axes, axis=axis, tiled=True),)

    return _custom("sp_reduce_scatter",
                   _rs_allgather_bwd(x._value, axes, axis), bwd, x)


# class-style aliases for reference API parity (PyLayer.apply surface)
class _OpAlias:
    def __init__(self, fn):
        self._fn = fn

    def apply(self, x, group=None, axis: int = 0):
        return self._fn(x, group=group, axis=axis)

    __call__ = apply


ScatterOp = _OpAlias(scatter)
GatherOp = _OpAlias(gather)
AllGatherOp = _OpAlias(all_gather)
ReduceScatterOp = _OpAlias(reduce_scatter)


def identity_in_sequence_parallel(x: Tensor) -> Tensor:
    return x


def _group(group):
    if group is not None:
        return group
    from ... import fleet as _fleet

    hcg = _fleet.get_hybrid_communicate_group()
    return hcg.get_model_parallel_group() if hcg is not None else None


# -- replicated-param grad sync markers -----------------------------------

def mark_as_sequence_parallel_parameter(parameter) -> None:
    """Mark a replicated parameter used on sequence-sharded activations
    (LayerNorm scales/biases, position embeddings). Its gradient is then
    psum'ed over mp inside the compiled step — the engine-side analog of
    the reference's allreduce hook (sequence_parallel_utils.py:156)."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter) -> bool:
    return bool(getattr(parameter, "sequence_parallel", False))


def register_sequence_parallel_allreduce_hooks(model,
                                               accumulation_steps: int = 1,
                                               fused_allreduce: bool = False):
    """Reference :192 registers backward hooks allreducing marked params'
    grads over mp. In the SPMD engine the psum happens inside the one
    compiled step, so this only validates the marks exist."""
    return [p for p in model.parameters()
            if is_sequence_parallel_parameter(p)]


# -- SP linears (reference :222 ColumnSequenceParallelLinear,
#    :286 RowSequenceParallelLinear) --------------------------------------

class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear whose input arrives sequence-sharded.

    Forward: all-gather input along seq → local matmul with the
    column-sharded weight. Backward of the gather is a reduce-scatter.
    ``gather_output`` must be False (reference enforces the same).
    """

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None,
                 seq_axis: int = 0):
        super().__init__()
        enforce(not gather_output,
                "ColumnSequenceParallelLinear requires gather_output=False")
        self._mp_group = mp_group
        self._seq_axis = seq_axis
        g = _group(mp_group)
        self.world_size = g.nranks if g is not None else 1
        self.is_mp = self.world_size > 1
        enforce(out_features % self.world_size == 0,
                f"out_features {out_features} must divide mp degree "
                f"{self.world_size}")
        self.in_features = in_features
        self.out_features = out_features
        from jax.sharding import PartitionSpec as P

        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        self.bias = self.create_parameter(
            (out_features,), attr=ParamAttr._to_attr(None), is_bias=True) \
            if has_bias else None
        if self.is_mp:
            self.weight.dist_attr = P(None, "mp")
            self.weight.is_distributed = True
            if self.bias is not None:
                self.bias.dist_attr = P("mp")
                self.bias.is_distributed = True

    def forward(self, x):
        if self.is_mp:
            from ... import collective_matmul as _cm

            axes = mp_axes(self._mp_group)
            if _cm.overlap_available(axes):
                # seq all-gather + matmul as one bidirectional ring: each
                # tick matmuls the resident seq shard while the next is
                # in flight (backward is the mirrored matmul_rs ring)
                return _cm.linear_ag_matmul(x, self.weight, self.bias,
                                            axes, self._seq_axis)
            x = all_gather(x, self._mp_group, axis=self._seq_axis)
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, sp")


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear whose output leaves sequence-sharded.

    Forward: local matmul with the row-sharded weight → reduce-scatter
    along seq (replacing plain TP's allreduce). Backward is an
    all-gather. ``input_is_parallel`` must be True (reference parity).
    """

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = True,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None,
                 seq_axis: int = 0):
        super().__init__()
        enforce(input_is_parallel,
                "RowSequenceParallelLinear requires input_is_parallel=True")
        self._mp_group = mp_group
        self._seq_axis = seq_axis
        g = _group(mp_group)
        self.world_size = g.nranks if g is not None else 1
        self.is_mp = self.world_size > 1
        enforce(in_features % self.world_size == 0,
                f"in_features {in_features} must divide mp degree "
                f"{self.world_size}")
        self.in_features = in_features
        self.out_features = out_features
        from jax.sharding import PartitionSpec as P

        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        self.bias = self.create_parameter(
            (out_features,), attr=ParamAttr._to_attr(None), is_bias=True) \
            if has_bias else None
        if self.is_mp:
            self.weight.dist_attr = P("mp", None)
            self.weight.is_distributed = True
            # bias replicated but applied on seq shards → grads need the
            # mp psum: mark it sequence-parallel
            if self.bias is not None:
                mark_as_sequence_parallel_parameter(self.bias)

    def forward(self, x):
        if self.is_mp:
            from ... import collective_matmul as _cm

            axes = mp_axes(self._mp_group)
            if _cm.overlap_available(axes) and _cm.scatter_divides(
                    x.shape[self._seq_axis], axes):
                # matmul + seq reduce-scatter as a ring of partial-sum
                # shifts: each tick's chunk-GEMM overlaps the in-flight
                # accumulator (backward is the mirrored ag_matmul ring)
                out = _cm.linear_matmul_rs(x, self.weight, None, axes,
                                           self._seq_axis)
                if self.bias is not None:
                    out = out + self.bias
                return out
        out = F.linear(x, self.weight, None)
        if self.is_mp:
            out = reduce_scatter(out, self._mp_group, axis=self._seq_axis)
        if self.bias is not None:
            out = out + self.bias
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, sp")
