"""Fleet utility namespace (reference: python/paddle/distributed/fleet/utils/).

``sequence_parallel_utils`` — Megatron-style sequence parallelism.
``recompute`` / ``hybrid_parallel_util`` helpers live at this level in the
reference; here grad sync is performed inside the compiled SPMD step, so
the hook-based helpers reduce to markers the engine reads.
"""
from . import sequence_parallel_utils  # noqa: F401
from .recompute import recompute  # noqa: F401

__all__ = ["sequence_parallel_utils", "recompute"]
