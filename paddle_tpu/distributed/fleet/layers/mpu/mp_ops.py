"""Tensor-parallel communication primitives.

TPU-native re-design of the reference's mp_ops
(reference: python/paddle/distributed/fleet/layers/mpu/mp_ops.py:83-285 —
_c_identity/_c_concat/_c_split/_mp_allreduce built on NCCL rings).

Here each primitive is a PyLayer-style custom-grad node whose forward /
backward are XLA collectives over the 'mp' mesh axis (psum/all_gather on
ICI). Outside an SPMD region (mp degree 1, or plain eager single chip)
every primitive is the identity, matching the reference's single-card
behavior.

Each primitive's value-level function carries a ``jax.custom_vjp`` rule
identical to the tape rule, so model forwards differentiate correctly
under BOTH the eager tape (`loss.backward()`) and pure function
transforms (`jax.vjp` — used by the pipeline-parallel schedule and
`jit.to_static`). Without the custom rule, shard_map's default psum
transpose would not implement the Megatron identity/allreduce pairing.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .... import collective as C
from .....autograd import engine as _engine
from .....tensor import Tensor

__all__ = ["_c_identity", "_c_concat", "_c_split", "_mp_allreduce",
           "mp_axes", "mp_active", "allgather_slice_bwd",
           "slice_allgather_bwd", "allgather_reducescatter_bwd",
           "reducescatter_allgather_bwd"]


def mp_axes(group: Optional[C.Group] = None):
    g = group
    if g is None:
        from .... import fleet as _fleet

        hcg = _fleet.get_hybrid_communicate_group()
        if hcg is not None:
            g = hcg.get_model_parallel_group()
    if g is None or not g.axis_names or g.nranks <= 1:
        return None
    return g.axis_names


def mp_active(group: Optional[C.Group] = None) -> bool:
    return C.in_spmd_region() and mp_axes(group) is not None


# -- value-level primitives with Megatron custom-vjp pairing -------------

def _act_psum(x, axes):
    """The TP activation allreduce both Megatron pairings issue:
    int8/fp8 wire when the quant_comm mp_rings knob is on (stateless —
    quant_comm.maybe_quantized_psum), the plain ledger shim
    otherwise."""
    from .... import quant_comm as _qc

    return _qc.maybe_quantized_psum(x, axes)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def identity_psum_bwd(x, axes):
    """Forward identity; backward psum over ``axes`` (f in Megatron)."""
    return x


identity_psum_bwd.defvjp(lambda x, axes: (x, None),
                         lambda axes, _, g: (_act_psum(g, axes),))


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_identity_bwd(x, axes):
    """Forward psum over ``axes``; backward identity (g in Megatron)."""
    return _act_psum(x, axes)


psum_identity_bwd.defvjp(lambda x, axes: (_act_psum(x, axes), None),
                         lambda axes, _, g: (g,))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def allgather_slice_bwd(x, axes, axis=-1):
    """Forward all-gather (tiled) along ``axis``; backward local slice."""
    return C.t_all_gather(x, axes, axis=axis % x.ndim, tiled=True)


def _ag_fwd(x, axes, axis):
    return allgather_slice_bwd(x, axes, axis), x.shape[axis]


def _ag_bwd(axes, axis, local, g):
    idx = C.axis_index(axes)
    return (lax.dynamic_slice_in_dim(g, idx * local, local, axis=axis),)


allgather_slice_bwd.defvjp(_ag_fwd, _ag_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def slice_allgather_bwd(x, axes, axis=-1):
    """Forward this rank's slice of ``axis``; backward all-gather."""
    n = 1
    for a in axes:
        n *= C.axis_size(a)
    local = x.shape[axis] // n
    idx = C.axis_index(axes)
    return lax.dynamic_slice_in_dim(x, idx * local, local, axis=axis)


slice_allgather_bwd.defvjp(
    lambda x, axes, axis: (slice_allgather_bwd(x, axes, axis), None),
    lambda axes, axis, _, g: (C.t_all_gather(g, axes, axis=axis % g.ndim,
                                             tiled=True),))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def allgather_reducescatter_bwd(x, axes, axis=0):
    """Forward all-gather along ``axis``; backward reduce-scatter (sum).
    The SP pairing (sequence_parallel_utils AllGatherOp)."""
    return C.t_all_gather(x, axes, axis=axis, tiled=True)


def _agrs_bwd(axes, axis, _, g):
    out = g
    for a in axes:
        out = C.t_psum_scatter(out, a, scatter_dimension=axis, tiled=True)
    return (out,)


allgather_reducescatter_bwd.defvjp(
    lambda x, axes, axis: (allgather_reducescatter_bwd(x, axes, axis), None),
    _agrs_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reducescatter_allgather_bwd(x, axes, axis=0):
    """Forward reduce-scatter (sum) along ``axis``; backward all-gather.
    The SP pairing (sequence_parallel_utils ReduceScatterOp)."""
    out = x
    for a in axes:
        out = C.t_psum_scatter(out, a, scatter_dimension=axis, tiled=True)
    return out


reducescatter_allgather_bwd.defvjp(
    lambda x, axes, axis: (reducescatter_allgather_bwd(x, axes, axis), None),
    lambda axes, axis, _, g: (C.t_all_gather(g, axes, axis=axis,
                                             tiled=True),))


def _custom(name, fwd_value, backward_fn, x: Tensor) -> Tensor:
    out = Tensor(fwd_value, stop_gradient=x.stop_gradient)
    if _engine.is_grad_enabled() and not x.stop_gradient:
        out.stop_gradient = False
        _engine.record_custom(name, backward_fn, [x], [out], fwd_value)
    return out


def _c_identity(x: Tensor, group: Optional[C.Group] = None) -> Tensor:
    """Forward identity; backward allreduces the grad over mp.

    Used at the input of ColumnParallelLinear (reference mp_ops.py:83).
    """
    if not mp_active(group):
        return x
    axes = mp_axes(group)

    def bwd(g):
        return (_act_psum(g, axes),)

    return _custom("c_identity", identity_psum_bwd(x._value, axes), bwd, x)


def _mp_allreduce(x: Tensor, group: Optional[C.Group] = None,
                  op=None) -> Tensor:
    """Forward allreduce over mp; backward identity.

    Used at the output of RowParallelLinear (reference mp_ops.py:248
    mp_allreduce_sum).
    """
    if not mp_active(group):
        return x
    axes = mp_axes(group)

    def bwd(g):
        return (g,)

    return _custom("mp_allreduce", psum_identity_bwd(x._value, axes), bwd, x)


def _c_concat(x: Tensor, group: Optional[C.Group] = None) -> Tensor:
    """Forward all-gather along the last dim; backward takes the local
    slice (reference mp_ops.py:171 _c_concat on the column output)."""
    if not mp_active(group):
        return x
    axes = mp_axes(group)
    local = x._value.shape[-1]

    def bwd(g):
        idx = C.axis_index(axes)
        return (lax.dynamic_slice_in_dim(g, idx * local, local, axis=-1),)

    return _custom("c_concat", allgather_slice_bwd(x._value, axes, -1), bwd, x)


def _c_split(x: Tensor, group: Optional[C.Group] = None) -> Tensor:
    """Forward takes this rank's slice of the last dim; backward
    all-gathers (reference mp_ops.py:212 _c_split)."""
    if not mp_active(group):
        return x
    axes = mp_axes(group)

    def bwd(g):
        return (C.t_all_gather(g, axes, axis=g.ndim - 1, tiled=True),)

    return _custom("c_split", slice_allgather_bwd(x._value, axes, -1), bwd, x)
