"""Hybrid-parallel RNG state tracking — fleet-facing surface.

(reference: python/paddle/distributed/fleet/layers/mpu/random.py:34,99 —
``RNGStatesTracker`` / ``get_rng_state_tracker`` / seed setup.)

The tracker implementation lives in core/rng.py (one singleton shared by
the whole framework); this module provides the fleet-named accessors and
the seed-derivation convention.
"""
from __future__ import annotations

from .....core import rng as _rng
from .....core.rng import GLOBAL_SEED, LOCAL_SEED, RNGStatesTracker

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "local_dropout_key",
           "LOCAL_SEED", "GLOBAL_SEED"]


def get_rng_state_tracker() -> RNGStatesTracker:
    return _rng.get_rng_tracker()


def model_parallel_random_seed(seed: int = 0) -> None:
    """(reference mp random.py:99) — derive distinct local/global seeds."""
    tracker = get_rng_state_tracker()
    tracker.reset()
    _rng.seed(seed)
    tracker.add(GLOBAL_SEED, seed)
    tracker.add(LOCAL_SEED, seed + 2718)


def local_dropout_key():
    """A PRNG key from the 'local_seed' stream (distinct per mp rank for
    mp-sharded tensors); falls back to the global stream when the tracker
    has not been seeded."""
    tracker = get_rng_state_tracker()
    if LOCAL_SEED in tracker.states_:
        with tracker.rng_state(LOCAL_SEED):
            return _rng.get_key()
    return _rng.get_key()
