from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding,
                        parallel_cross_entropy)
from .mp_ops import _c_concat, _c_identity, _c_split, _mp_allreduce
from .random import (RNGStatesTracker, get_rng_state_tracker,
                     model_parallel_random_seed)

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "parallel_cross_entropy", "RNGStatesTracker",
    "get_rng_state_tracker", "model_parallel_random_seed",
]
