"""Tensor-parallel layers over the TPU mesh.

TPU-native re-design of the reference's Megatron-style TP layers
(reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:333,
RowParallelLinear:540, ParallelCrossEntropy:741).

Design difference from the reference: each layer creates its parameter at
the FULL logical shape and annotates it with a ``jax.sharding.PartitionSpec``
in ``param.dist_attr``. A single-controller jax program then stores the
parameter as one global jax.Array physically sharded over the 'mp' mesh
axis; inside the SPMD train step (shard_map) the layer sees only its local
shard and the collectives below ride ICI. Outside an SPMD region the same
layer computes the exact single-device result — which is what makes the
reference's loss-parity test strategy (SURVEY.md §4) directly expressible.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .... import collective as C
from .....autograd import engine as _engine
from .....core.dispatch import def_op
from .....core.enforce import enforce
from .....nn import functional as F
from .....nn.layer import Layer
from .....framework.param_attr import ParamAttr
from .....tensor import Tensor
from .mp_ops import _c_concat, _c_identity, _c_split, _mp_allreduce, mp_axes

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_world(mp_group):
    if mp_group is not None:
        return mp_group.nranks
    from .... import fleet as _fleet

    hcg = _fleet.get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg is not None else 1


@def_op("c_embedding")
def _c_embedding(w, ids, axes=()):
    """Masked local-shard lookup (reference:
    paddle/phi/kernels/gpu/c_embedding_kernel.cu — rows outside this
    rank's [off, off+vloc) produce zeros; grads flow by generic vjp as a
    local scatter-add)."""
    vloc = w.shape[0]
    idx = C.axis_index(axes)
    off = idx * vloc
    local = jnp.clip(ids - off, 0, vloc - 1)
    mask = (ids >= off) & (ids < off + vloc)
    out = jnp.take(w, local, axis=0)
    return jnp.where(mask[..., None], out, jnp.zeros((), out.dtype))


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp
    (reference mp_layers.py:47)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._mp_group = mp_group
        self.world_size = _mp_world(mp_group)
        self.is_mp = self.world_size > 1
        enforce(num_embeddings % self.world_size == 0,
                f"vocab size {num_embeddings} must divide mp degree "
                f"{self.world_size}")
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=ParamAttr._to_attr(weight_attr))
        if self.is_mp:
            self.weight.dist_attr = P("mp", None)
            self.weight.is_distributed = True

    def forward(self, x):
        axes = mp_axes(self._mp_group)
        if self.is_mp and C.in_spmd_region() and axes is not None:
            out = _c_embedding(self.weight, x, axes=axes)
            return _mp_allreduce(out, self._mp_group)
        return F.embedding(x, self.weight)

    def extra_repr(self):
        return (f"{self.num_embeddings}, {self.embedding_dim}, "
                f"mp={self.world_size}")


class ColumnParallelLinear(Layer):
    """Linear with the output dim sharded over mp; Y_local = X @ W_local
    (reference mp_layers.py:333). Backward of the input identity is an
    mp allreduce."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self._mp_group = mp_group
        self.world_size = _mp_world(mp_group)
        self.is_mp = self.world_size > 1
        enforce(out_features % self.world_size == 0,
                f"out_features {out_features} must divide mp degree "
                f"{self.world_size}")
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        self.bias = self.create_parameter(
            (out_features,), attr=ParamAttr._to_attr(None), is_bias=True) \
            if has_bias else None
        if self.is_mp:
            self.weight.dist_attr = P(None, "mp")
            self.weight.is_distributed = True
            if self.bias is not None:
                self.bias.dist_attr = P("mp")
                self.bias.is_distributed = True

    def forward(self, x):
        if self.is_mp:
            x = _c_identity(x, self._mp_group)
        if self.gather_output and self.is_mp:
            from .... import collective_matmul as _cm

            axes = mp_axes(self._mp_group)
            if _cm.overlap_available(axes):
                # gather side overlapped: the matmul is chunked over rows
                # so each chunk's feature all-gather pipelines behind the
                # next chunk's GEMM. The mp-sharded bias gathers once
                # (tiny) and adds after — same value as the unfused
                # pre-gather add.
                nchunks = _cm.chunk_count(x.shape[0], axes)
                out = _cm.linear_matmul_gather(x, self.weight, None, axes,
                                               nchunks)
                if self.bias is not None:
                    out = out + _c_concat(self.bias, self._mp_group)
                return out
            return _c_concat(F.linear(x, self.weight, self.bias),
                             self._mp_group)
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, gather_output={self.gather_output}")


class RowParallelLinear(Layer):
    """Linear with the input dim sharded over mp; Y = allreduce(X_local @
    W_local) (reference mp_layers.py:540). Bias is added after the
    allreduce so it contributes once."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self._mp_group = mp_group
        self.world_size = _mp_world(mp_group)
        self.is_mp = self.world_size > 1
        enforce(in_features % self.world_size == 0,
                f"in_features {in_features} must divide mp degree "
                f"{self.world_size}")
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        self.bias = self.create_parameter(
            (out_features,), attr=ParamAttr._to_attr(None), is_bias=True) \
            if has_bias else None
        if self.is_mp:
            self.weight.dist_attr = P("mp", None)
            self.weight.is_distributed = True
            # bias replicated: added once, after the allreduce

    def forward(self, x):
        if self.is_mp and not self.input_is_parallel:
            x = _c_split(x, self._mp_group)
        if self.is_mp:
            from .... import collective_matmul as _cm

            axes = mp_axes(self._mp_group)
            if _cm.overlap_available(axes):
                # reduce side overlapped: the allreduce's reduce-scatter
                # half rides a partial-sum ring behind the chunked GEMM;
                # falls through unfused when no leading dim divides the
                # ring (pick_scatter_axis None)
                ax = _cm.pick_scatter_axis(x.shape, axes)
                if ax is not None:
                    return _cm.linear_matmul_allreduce(
                        x, self.weight, self.bias, axes, ax)
            out = _mp_allreduce(F.linear(x, self.weight, None),
                                self._mp_group)
        else:
            out = F.linear(x, self.weight, None)
        if self.bias is not None:
            out = out + self.bias
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"mp={self.world_size}, "
                f"input_is_parallel={self.input_is_parallel}")


def parallel_cross_entropy(logits: Tensor, label: Tensor, mp_group=None,
                           ignore_index: int = -100) -> Tensor:
    """Softmax cross-entropy over vocab-sharded logits
    (reference: fluid/operators/collective/c_softmax_with_cross_entropy_op.cu;
    python wrapper mp_layers.py:741 ParallelCrossEntropy).

    Stable log-sum-exp with two mp collectives (pmax + psum); the backward
    is the classic (softmax - onehot) computed locally per shard.
    Returns loss of shape label.shape + [1] (reference parity).
    """
    axes = mp_axes(mp_group)
    if not C.in_spmd_region() or axes is None:
        from .....ops import manipulation as _mp

        loss = F.cross_entropy(logits, label, reduction="none",
                               ignore_index=ignore_index)
        return _mp.unsqueeze(loss, axis=-1)  # shape parity with SPMD path

    lab = label._value
    in_dtype = logits._value.dtype
    # softmax statistics in float32 (the non-mp path's log_softmax does the
    # same) so bf16 mp training keeps loss parity with single-device
    lv = logits._value.astype(jnp.float32)
    if lab.ndim == lv.ndim:          # [..., 1] labels accepted like paddle
        lab = lab.reshape(lab.shape[:-1])

    if _engine.is_grad_enabled() and not logits.stop_gradient:
        # tape path: one forward, residuals reused by the tape's bwd
        loss, res = _pce_fwd_impl(lv, lab, tuple(axes), int(ignore_index))
        out = Tensor(loss, stop_gradient=False)

        def bwd(g):
            gl, _ = _pce_bwd_impl(tuple(axes), int(ignore_index), res, g)
            return (gl.astype(in_dtype), None)

        _engine.record_custom("parallel_cross_entropy", bwd,
                              [logits, label], [out], loss)
        return out
    # no-grad path (e.g. inside a jax.vjp'd pp stage-owned epilogue):
    # the custom_vjp on _pce_raw supplies the correct gradient there
    loss = _pce_raw(lv, lab, tuple(axes), int(ignore_index))
    return Tensor(loss, stop_gradient=logits.stop_gradient)


def _pce_fwd_impl(lv, lab, axes, ignore_index):
    vloc = lv.shape[-1]
    idx = C.axis_index(axes)
    off = idx * vloc
    # pmax input is stop_gradient'ed: the LSE max-shift is gradient-free
    # mathematically and pmax has no differentiation rule
    maxl = C.t_pmax(
        lax.stop_gradient(jnp.max(lv, axis=-1, keepdims=True)), axes)
    shifted = lv - maxl
    expx = jnp.exp(shifted)
    sumexp = C.t_psum(jnp.sum(expx, axis=-1, keepdims=True), axes)
    local_lab = jnp.clip(lab - off, 0, vloc - 1)
    in_shard = (lab >= off) & (lab < off + vloc)
    tgt = jnp.take_along_axis(shifted, local_lab[..., None], axis=-1)[..., 0]
    tgt = C.t_psum(jnp.where(in_shard, tgt, jnp.zeros((), lv.dtype)), axes)
    valid = lab != ignore_index
    loss = jnp.where(valid, jnp.log(sumexp[..., 0]) - tgt,
                     jnp.zeros((), lv.dtype))[..., None]
    softmax = expx / sumexp
    onehot = (jnp.arange(vloc) == local_lab[..., None]) & in_shard[..., None]
    return loss, (softmax, onehot, valid)


def _pce_bwd_impl(axes, ignore_index, res, g):
    softmax, onehot, valid = res
    gl = (softmax - onehot.astype(softmax.dtype)) * g
    gl = jnp.where(valid[..., None], gl, jnp.zeros((), gl.dtype))
    return gl, None


from functools import partial as _partial  # noqa: E402


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _pce_raw(lv, lab, axes, ignore_index):
    """Value-level parallel cross entropy with its own vjp: under
    shard_map the transpose of psum is psum, so naive autodiff would
    multiply the replicated cotangent by the mp degree — the custom
    rule computes the classic (softmax - onehot) locally instead.
    (Needed when the loss is jax.vjp'd inside a pp stage-owned
    epilogue, pp_layers.py:_owned_apply.)"""
    return _pce_fwd_impl(lv, lab, axes, ignore_index)[0]


_pce_raw.defvjp(
    lambda lv, lab, axes, ignore_index:
    _pce_fwd_impl(lv, lab, axes, ignore_index),
    _pce_bwd_impl)


class ParallelCrossEntropy(Layer):
    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self._mp_group = mp_group
        self._ignore_index = ignore_index

    def forward(self, logits, label):
        return parallel_cross_entropy(logits, label, self._mp_group,
                                      self._ignore_index)
