"""Dygraph meta-optimizers: gradient merge, LARS, DGC, LocalSGD.

(reference: python/paddle/distributed/fleet/meta_optimizers/ —
gradient_merge_optimizer.py (static pass accumulating grads over
k_steps), lars_optimizer.py (LARS layer-wise adaptive rate over
Momentum), dgc_optimizer.py (deep gradient compression: top-k
sparsified momentum with error feedback), localsgd_optimizer.py
(local steps + periodic parameter averaging).)

TPU-native: the reference implements these as static-graph program
passes; here each is an eager optimizer wrapper over the SAME tape/
step machinery every optimizer uses — jit/to_static traces straight
through them. Grad sync itself belongs to the engine/collectives; these
wrappers own the update POLICY.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from ....autograd import no_grad
from ....tensor import Tensor

__all__ = ["GradientMergeOptimizer", "DGCMomentumOptimizer",
           "LocalSGDOptimizer"]


class GradientMergeOptimizer:
    """Accumulate grads over ``k_steps`` calls, then one inner step
    (reference gradient_merge_optimizer.py — the dygraph analog of the
    GradientMergePass: same math as a k-times-larger batch)."""

    def __init__(self, inner_optimizer, k_steps: int = 1, avg: bool = True):
        self._inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg
        self._acc: Dict[int, tuple] = {}  # id -> (param, summed grad)
        self._count = 0

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    @no_grad()
    def step(self):
        self._count += 1
        params = [p for p in self._inner_opt._parameter_list
                  if p is not None and p.grad is not None and p.trainable]
        for p in params:
            g = p.grad._value
            prev = self._acc.get(id(p))
            self._acc[id(p)] = (p, g if prev is None else prev[1] + g)
        if self._count % self.k_steps:
            # merge-only step: the inner optimizer must not see grads
            for p in params:
                p.grad = None
            return
        # apply EVERY accumulator (a param may lack a grad on the merge
        # step itself — its earlier micro-grads still count), then clear
        for p, g in self._acc.values():
            if self.avg:
                g = g / self.k_steps
            p.grad = Tensor(g, stop_gradient=True)
        self._acc.clear()
        self._inner_opt.step()

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)


class DGCMomentumOptimizer:
    """Deep Gradient Compression over momentum (reference
    dgc_optimizer.py / phi dgc kernels): per-parameter top-k gradient
    sparsification with error feedback — the dropped mass accumulates
    locally and re-enters the next step, so convergence follows the
    dense trajectory while each step only communicates ~(1-sparsity) of
    the gradient entries."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 sparsity=0.9, rampup_begin_step: int = 0,
                 weight_decay=None, grad_clip=None):
        from ....optimizer import Momentum

        self._inner_opt = Momentum(learning_rate=learning_rate,
                                   momentum=momentum, parameters=parameters,
                                   weight_decay=weight_decay,
                                   grad_clip=grad_clip)
        self.sparsity = float(sparsity)
        self.rampup_begin_step = int(rampup_begin_step)
        self._err: Dict[int, jnp.ndarray] = {}
        self._steps = 0

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def _compress(self, g):
        """Keep the top-(1-sparsity) entries by magnitude; return the
        sparse gradient and the residual (error feedback)."""
        flat = g.reshape(-1)
        k = max(1, int(flat.size * (1.0 - self.sparsity)))
        thresh = jnp.sort(jnp.abs(flat))[-k]
        keep = jnp.abs(g) >= thresh
        sparse = jnp.where(keep, g, 0.0)
        return sparse, g - sparse

    @no_grad()
    def step(self):
        self._steps += 1
        if self._steps > self.rampup_begin_step:
            for p in self._inner_opt._parameter_list:
                if p is None or p.grad is None or not p.trainable:
                    continue
                g = p.grad._value + self._err.get(id(p), 0.0)
                sparse, err = self._compress(g)
                self._err[id(p)] = err
                p.grad = Tensor(sparse, stop_gradient=True)
        self._inner_opt.step()

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)


class LocalSGDOptimizer:
    """Local steps + periodic cross-replica parameter averaging
    (reference localsgd_optimizer.py): between syncs each replica runs
    independent SGD; every ``k_steps`` the params are averaged over the
    dp world via the host object collectives."""

    def __init__(self, inner_optimizer, k_steps: int = 1):
        self._inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self._count = 0

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    @no_grad()
    def step(self):
        self._inner_opt.step()
        self._count += 1
        if self._count % self.k_steps == 0:
            self.sync_params()

    def sync_params(self):
        """rank-0 reduce + broadcast over the host object channel —
        O(P) traffic/memory per non-root host (an N-way all-gather of
        every parameter would be O(N x P) on every host)."""
        from ...runtime import process_rank, process_world

        world = process_world()
        if world <= 1:
            return
        import numpy as np

        from ... import broadcast_object_list, gather_object

        rank = process_rank()
        for p in self._inner_opt._parameter_list:
            if p is None or not p.trainable:
                continue
            gathered = gather_object(np.asarray(p._value), dst=0)
            if rank == 0:
                acc = gathered[0].astype(np.float64)
                for g in gathered[1:]:
                    acc += g
                mean = [(acc / world).astype(np.asarray(p._value).dtype)]
            else:
                mean = [None]
            broadcast_object_list(mean, src=0)
            p._value = jnp.asarray(mean[0], p._value.dtype)

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)
