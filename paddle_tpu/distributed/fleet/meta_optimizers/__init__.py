"""meta_optimizers: optimizer wrappers for hybrid parallel training.

(reference: python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/hybrid_parallel_optimizer.py — HybridParallelOptimizer
wraps the inner optimizer, syncs grads across groups and clips by
hybrid-global norm; dygraph_sharding_optimizer.py — sharding stage 1.)

TPU-native: gradient sync and sharded-state placement happen inside the
compiled train step (ParallelEngine), so the wrapper's job is state
partitioning policy + API surface. The engine unwraps ``_inner_opt``.
"""
from __future__ import annotations

from .dygraph_optimizer import DygraphShardingOptimizer, \
    HybridParallelOptimizer
from .extra import (DGCMomentumOptimizer, GradientMergeOptimizer,
                    LocalSGDOptimizer)

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer",
           "GradientMergeOptimizer", "DGCMomentumOptimizer",
           "LocalSGDOptimizer"]
