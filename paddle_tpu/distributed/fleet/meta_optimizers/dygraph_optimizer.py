"""HybridParallelOptimizer + sharding-stage-1 optimizer.

(reference: fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py — wraps the user optimizer, syncs grads over
dp/sharding groups, rescales, hybrid-aware grad clip;
dygraph_sharding_optimizer.py:224,294,317 — DygraphShardingOptimizer
partitions params greedily by size across the sharding group, reduces
grads to the owner, broadcasts updated params.)

TPU-native: the ParallelEngine performs grad sync (psum/pmean over mesh
axes) and places optimizer state per PartitionSpec inside the compiled
step, with donated buffers. The wrappers here carry the *policy*:

- ``HybridParallelOptimizer`` — API surface + hybrid grad clip.
- ``DygraphShardingOptimizer`` — ZeRO-1: marks every parameter's
  optimizer state to be sharded over the 'sharding' mesh axis (dim 0 when
  divisible). The engine reads ``state_partition_axis`` and gives moment
  buffers a NamedSharding over that axis, so each rank physically stores
  1/sharding of the moments — the memory effect of the reference's
  greedy parameter partitioning, with XLA doing the reduce-scatter /
  all-gather placement.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]


class _OptimizerWrapper:
    def __init__(self, optimizer, hcg=None, strategy=None):
        inner = getattr(optimizer, "_inner_opt", optimizer)
        self._inner_opt = inner
        self._hcg = hcg
        self._strategy = strategy

    # everything not overridden delegates to the inner optimizer
    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    # _step_count is read AND written by the engine (`opt._step_count += 1`);
    # without a data descriptor the write would shadow the inner counter and
    # state_dict() would save a frozen step (wrong Adam bias correction on
    # resume)
    @property
    def _step_count(self):
        return self._inner_opt._step_count

    @_step_count.setter
    def _step_count(self, v):
        self._inner_opt._step_count = v

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, set_to_zero: bool = True):
        return self._inner_opt.clear_grad(set_to_zero)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state_dict):
        return self._inner_opt.set_state_dict(state_dict)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, lr):
        return self._inner_opt.set_lr(lr)


class HybridParallelOptimizer(_OptimizerWrapper):
    """(reference hybrid_parallel_optimizer.py — mp/pp-aware wrapper)"""

    def __init__(self, optimizer, hcg=None, strategy=None):
        super().__init__(optimizer, hcg, strategy)
        sharding_degree = (hcg.get_sharding_parallel_world_size()
                           if hcg is not None else 1)
        if sharding_degree > 1:
            # fleet auto-applies stage-1 sharding when the axis exists
            self._inner_opt.state_partition_axis = "sharding"


class DygraphShardingOptimizer(_OptimizerWrapper):
    """ZeRO stage 1 (reference dygraph_sharding_optimizer.py).

    The reference partitions parameters greedily by size
    (_partition_parameters:224) and makes each rank update only its
    shard, then broadcasts. Here the partitioning is declarative: moment
    buffers get a 'sharding'-axis PartitionSpec (dim 0) and XLA owns the
    data movement; the update math is unchanged.
    """

    def __init__(self, optimizer, hcg=None):
        super().__init__(optimizer, hcg)
        self._inner_opt.state_partition_axis = "sharding"

    def reduce_gradients(self, parameter_list=None, hcg=None):
        """No-op: grad reduction happens inside the compiled step
        (reference :294 reduces to the owner rank over NCCL)."""

    def _sharding_sync_parameters(self):
        """No-op: params are global jax.Arrays (reference :317 broadcasts
        updated shards)."""
