"""Collective matmul: chunked ring decompositions that hide ICI transfer
behind partial GEMMs (T3, arXiv:2401.16677; reference knob:
``strategy.hybrid_configs["mp_configs"]["mp_async_allreduce"]`` —
python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py async allreduce overlap).

XLA already overlaps collectives with *independent* compute (the
latency-hiding scheduler), but it cannot break a data dependence: an
``all_gather`` feeding a matmul, or a matmul feeding a
``reduce_scatter``/``all_reduce``, serializes — the whole-tensor
collective is exposed on the step's critical path. The decompositions
here re-express those fused pairs as a ring of per-shard steps, so each
tick's GEMM has no dependence on that tick's ``lax.ppermute`` and the
scheduler hides the transfer behind the partial matmul:

- ``ag_matmul(x, w)``     = ``all_gather(x) @ w``: a bidirectional
  ppermute ring; each tick matmuls the resident shard (writing its slice
  of the output) while the next shard is in flight from both neighbors.
- ``matmul_rs(x, w)``     = ``psum_scatter(x @ w)``: a ring of
  partial-sum shifts; each tick computes the output chunk destined for
  the accumulator currently passing through and adds it before the shift.
- ``matmul_allreduce``    = ``psum(x @ w)`` as matmul_rs + all_gather:
  the reduce half of the allreduce rides behind the GEMM
  (RowParallelLinear's reduce side).
- ``matmul_gather``       = ``all_gather(x @ w, axis=-1)`` chunked over
  rows so each chunk's feature gather overlaps the next chunk's GEMM
  (ColumnParallelLinear's gather side).

Each op carries a ``jax.custom_vjp`` whose backward is the mirrored ring
(bwd of ag_matmul is matmul_rs-shaped and vice versa), so the backward
pass overlaps the same way — and matches the Megatron/SP custom-grad
pairings of the unfused layers exactly (mp_ops.py /
sequence_parallel_utils.py), keeping loss parity with the knob off.

Fallback policy (``overlap_available``): the ring needs one concrete
mesh axis (a single-name mp group) inside an SPMD region, and the
chunked dim must divide the ring size; anything else runs the unfused
layer path unchanged.

Quantized ring ticks (``strategy.hybrid_configs["quant_comm"]`` with
``mp_rings`` on — distributed/quant_comm.py): every ppermute/
all_gather payload of the ag_matmul / matmul_rs / matmul_allreduce
rings (and their mirrored backward rings) ships as int8/fp8 + bf16
per-chunk scales instead of the activation dtype. Travelling shards
(ag ring, the weight-grad ring) quantize ONCE at ring entry and
dequantize per tick for the partial GEMM — multi-hop shards see
exactly one quantization; the matmul_rs accumulator re-quantizes per
shift because its value changes each tick (one quantization step of
error per hop, the EQuARX trade — stateless, activations carry no
error-feedback state across steps). The custom VJPs reuse the same
(maybe-quantized) ring bodies, so forward/backward stay mirrored and
tpulint's vjp-ledger-symmetry pairing is unchanged. matmul_gather's
output gather stays full precision (its payload is the layer OUTPUT
feature gather — quantizing it would compress activations handed to
arbitrary downstream math, not a ring-internal partial).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from . import collective as C
from ..autograd import engine as _engine
from ..tensor import Tensor

__all__ = [
    "ag_matmul", "matmul_rs", "matmul_allreduce", "matmul_gather",
    "overlap_enabled", "overlap_available",
    "moe_a2a_ffn", "moe_overlap_enabled", "moe_overlap_available",
    "linear_ag_matmul", "linear_matmul_rs", "linear_matmul_allreduce",
    "linear_matmul_gather",
    "pick_scatter_axis", "scatter_divides", "chunk_count",
]


# -- knob -----------------------------------------------------------------

def overlap_enabled() -> bool:
    """The reference knob, read live from the active fleet strategy
    (fleet.init / TensorParallel plumb it into the fleet state)."""
    from . import fleet as _fleet

    strat = _fleet.get_strategy()
    if strat is None:
        return False
    mp_cfg = strat.hybrid_configs.get("mp_configs") or {}
    return bool(mp_cfg.get("mp_async_allreduce", False))


def _ring_axis(axes) -> Optional[str]:
    """The single concrete mesh axis a ppermute ring can run over, or
    None (multi-axis mp groups fall back to the unfused path)."""
    if not axes:
        return None
    flat = []
    for a in axes:
        flat.extend(a if isinstance(a, (tuple, list)) else (a,))
    return flat[0] if len(flat) == 1 else None


def overlap_available(axes) -> bool:
    """True when the fused ring path may run: knob on, inside an SPMD
    region, over exactly one mesh axis."""
    return (overlap_enabled() and C.in_spmd_region()
            and _ring_axis(axes) is not None)


# -- ring building blocks -------------------------------------------------

def _mm(c, w):
    """c [..., k] @ w [k, n] — the same contraction F.linear lowers to."""
    return lax.dot_general(c, w, (((c.ndim - 1,), (0,)), ((), ())))


def _tdot(a, b):
    """Contract ALL leading dims: a [..., k], b [..., n] -> [k, n]
    (the weight-grad contraction of a linear on >=2-d activations)."""
    dims = tuple(range(a.ndim - 1))
    return lax.dot_general(a, b, ((dims, dims), ((), ())))


def _ring_info(axes):
    name = _ring_axis(axes)
    return name, C.axis_size(name), lax.axis_index(name)


def _ring_qcfg(p: int):
    """The active ring quantization config (or None): the quant_comm
    knob's mp_rings half, read live from the fleet strategy at trace
    time exactly like overlap_enabled(). p == 1 rings move no bytes —
    nothing to compress."""
    if p <= 1:
        return None
    from . import quant_comm as _qc

    return _qc.ring_config()


def _perms(p):
    up = [(i, (i + 1) % p) for i in range(p)]    # recv from idx - t
    dn = [(i, (i - 1) % p) for i in range(p)]    # recv from idx + t
    return up, dn


# Semantic trace scopes (observability.annotate): each ring is named in
# the XLA metadata, so a Perfetto/TensorBoard device trace shows e.g.
# `ag_matmul_ring` spanning the ppermute+GEMM ticks instead of a soup
# of anonymous dynamic-update-slices — the first thing to look at when
# asking "which collective ate the step".
def _ag_matmul_impl(x, w, axes, axis):
    from ..observability import annotate as _annotate

    with _annotate("ag_matmul_ring"):
        return _ag_matmul_body(x, w, axes, axis)


def _matmul_rs_impl(x, w, axes, axis):
    from ..observability import annotate as _annotate

    with _annotate("matmul_rs_ring"):
        return _matmul_rs_body(x, w, axes, axis)


def _matmul_allreduce_impl(x, w, axes, axis):
    from ..observability import annotate as _annotate

    with _annotate("matmul_allreduce_ring"):
        return _matmul_allreduce_body(x, w, axes, axis)


def _matmul_gather_impl(x, w, axes, nchunks):
    from ..observability import annotate as _annotate

    with _annotate("matmul_gather_ring"):
        return _matmul_gather_body(x, w, axes, nchunks)


def _ag_matmul_body(x, w, axes, axis):
    """all_gather(x, axis, tiled) @ w as a bidirectional ppermute ring.

    Each tick issues the next shard's permutes FIRST, then matmuls the
    resident shard into its output slice — the permute has no dependence
    on the matmul, so XLA's latency-hiding scheduler runs them
    concurrently on ICI + MXU.
    """
    name, p, idx = _ring_info(axes)
    local = x.shape[axis]
    chunk0 = _mm(x, w)
    shape = list(chunk0.shape)
    shape[axis] = local * p
    out = jnp.zeros(tuple(shape), chunk0.dtype)

    def place(buf, chunk, pos):
        return lax.dynamic_update_slice_in_dim(buf, chunk, pos * local,
                                               axis=axis)

    out = place(out, chunk0, idx)
    if p == 1:
        return out
    up_perm, dn_perm = _perms(p)
    qc = _ring_qcfg(p)
    if qc is not None:
        # quantize the resident shard ONCE; the (payload, scales) pair
        # travels the ring and each tick dequantizes for its GEMM
        from . import quant_comm as _qc

        ratio = _qc.block_ratio(x.shape, x.dtype, qc)
        uq, us = _qc.pack_block(x, qc)
        dq, ds = uq, us
        for t in range(1, (p - 1) // 2 + 1):
            uq, us = _qc.permute_packed(uq, us, name, up_perm, ratio)
            dq, ds = _qc.permute_packed(dq, ds, name, dn_perm, ratio)
            out = place(out, _mm(_qc.unpack_block(
                uq, us, x.shape, x.dtype, qc), w), (idx - t) % p)
            out = place(out, _mm(_qc.unpack_block(
                dq, ds, x.shape, x.dtype, qc), w), (idx + t) % p)
        if p % 2 == 0:
            uq, us = _qc.permute_packed(uq, us, name, up_perm, ratio)
            out = place(out, _mm(_qc.unpack_block(
                uq, us, x.shape, x.dtype, qc), w), (idx - p // 2) % p)
        return out
    up = dn = x
    for t in range(1, (p - 1) // 2 + 1):
        up = C.t_ppermute(up, name, up_perm)
        dn = C.t_ppermute(dn, name, dn_perm)
        out = place(out, _mm(up, w), (idx - t) % p)
        out = place(out, _mm(dn, w), (idx + t) % p)
    if p % 2 == 0:
        up = C.t_ppermute(up, name, up_perm)
        out = place(out, _mm(up, w), (idx - p // 2) % p)
    return out


def _matmul_rs_body(x, w, axes, axis):
    """psum_scatter(x @ w, axis, tiled) as a ring of partial-sum shifts.

    The accumulator destined for rank d is created at rank d+1 and
    travels i -> i-1; each rank adds its chunk-GEMM for the passing
    destination. The GEMM of tick t is independent of tick t-1's
    ppermute, so they overlap.
    """
    name, p, idx = _ring_info(axes)
    local = x.shape[axis] // p

    def chunk(j):
        return lax.dynamic_slice_in_dim(x, j * local, local, axis=axis)

    acc = _mm(chunk((idx + 1) % p), w)
    if p == 1:
        return acc
    perm = [(i, (i - 1) % p) for i in range(p)]
    qc = _ring_qcfg(p)
    if qc is not None:
        # the accumulator CHANGES each tick (partial sums), so it
        # re-quantizes before every shift — one quantization step of
        # error per hop, dequantized back to the working dtype so the
        # adds themselves stay full precision
        from . import quant_comm as _qc

        ratio = _qc.block_ratio(acc.shape, acc.dtype, qc)
        for t in range(1, p):
            q, s = _qc.pack_block(acc, qc)
            q, s = _qc.permute_packed(q, s, name, perm, ratio)
            nxt = _qc.unpack_block(q, s, acc.shape, acc.dtype, qc)
            acc = nxt + _mm(chunk((idx + 1 + t) % p), w)
        return acc
    for t in range(1, p):
        nxt = C.t_ppermute(acc, name, perm)
        acc = nxt + _mm(chunk((idx + 1 + t) % p), w)
    return acc


def _grad_w_ring(shard, full, axes, axis):
    """sum_j shard_from_rank_j^T . slice_j(full): the weight-grad of a
    gathered-input linear, computed as the same bidirectional ring so
    the backward's all-gather hides behind the per-chunk contractions.
    shard [..., a], full [..., b] (full's ``axis`` dim = p * shard's)
    -> [a, b]."""
    name, p, idx = _ring_info(axes)
    local = shard.shape[axis]

    def sl(j):
        return lax.dynamic_slice_in_dim(full, j * local, local, axis=axis)

    dw = _tdot(shard, sl(idx))
    if p == 1:
        return dw
    up_perm, dn_perm = _perms(p)
    qc = _ring_qcfg(p)
    if qc is not None:
        # travelling shard: quantize once, dequantize per tick (the
        # same discipline as the ag ring — this IS ag_matmul's bwd)
        from . import quant_comm as _qc

        ratio = _qc.block_ratio(shard.shape, shard.dtype, qc)
        uq, us = _qc.pack_block(shard, qc)
        dq, ds = uq, us
        for t in range(1, (p - 1) // 2 + 1):
            uq, us = _qc.permute_packed(uq, us, name, up_perm, ratio)
            dq, ds = _qc.permute_packed(dq, ds, name, dn_perm, ratio)
            dw = dw \
                + _tdot(_qc.unpack_block(uq, us, shard.shape,
                                         shard.dtype, qc),
                        sl((idx - t) % p)) \
                + _tdot(_qc.unpack_block(dq, ds, shard.shape,
                                         shard.dtype, qc),
                        sl((idx + t) % p))
        if p % 2 == 0:
            uq, us = _qc.permute_packed(uq, us, name, up_perm, ratio)
            dw = dw + _tdot(_qc.unpack_block(uq, us, shard.shape,
                                             shard.dtype, qc),
                            sl((idx - p // 2) % p))
        return dw
    up = dn = shard
    for t in range(1, (p - 1) // 2 + 1):
        up = C.t_ppermute(up, name, up_perm)
        dn = C.t_ppermute(dn, name, dn_perm)
        dw = dw + _tdot(up, sl((idx - t) % p)) + _tdot(dn, sl((idx + t) % p))
    if p % 2 == 0:
        up = C.t_ppermute(up, name, up_perm)
        dw = dw + _tdot(up, sl((idx - p // 2) % p))
    return dw


# -- value-level fused ops with mirrored-ring custom VJPs -----------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def ag_matmul(x, w, axes, axis=0):
    """all_gather(x, axis) @ w, overlapped. Pairing (SP column linear):
    the gather's bwd is a reduce-scatter — so d(x) is matmul_rs-shaped
    and d(w) is the gather-ring contraction."""
    return _ag_matmul_impl(x, w, axes, axis)


def _ag_matmul_fwd(x, w, axes, axis):
    return _ag_matmul_impl(x, w, axes, axis), (x, w)


def _ag_matmul_bwd(axes, axis, res, g):
    x, w = res
    dx = _matmul_rs_impl(g, w.T, axes, axis)
    dw = _grad_w_ring(x, g, axes, axis)
    return dx, dw


ag_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_rs(x, w, axes, axis=0):
    """psum_scatter(x @ w, axis), overlapped. Pairing (SP row linear):
    the scatter's bwd is an all-gather — so d(x) is ag_matmul-shaped."""
    return _matmul_rs_impl(x, w, axes, axis)


def _matmul_rs_fwd(x, w, axes, axis):
    return _matmul_rs_impl(x, w, axes, axis), (x, w)


def _matmul_rs_bwd(axes, axis, res, g):
    x, w = res
    dx = _ag_matmul_impl(g, w.T, axes, axis)
    dw = _grad_w_ring(g, x, axes, axis).T
    return dx, dw


matmul_rs.defvjp(_matmul_rs_fwd, _matmul_rs_bwd)


def _matmul_allreduce_body(x, w, axes, axis):
    out = _matmul_rs_body(x, w, axes, axis)
    name, p, _ = _ring_info(axes)
    qc = _ring_qcfg(p)
    if qc is not None:
        # the gather half of the allreduce ships quantized too: pack
        # the summed shard once, all_gather payload + scales, and
        # reassemble the rank blocks along the scattered dim
        from . import quant_comm as _qc

        ratio = _qc.block_ratio(out.shape, out.dtype, qc)
        q, s = _qc.pack_block(out, qc)
        qg, sg = _qc.gather_packed(q, s, axes, ratio)
        blocks = [_qc.unpack_block(qg[j], sg[j], out.shape, out.dtype,
                                   qc) for j in range(p)]
        return jnp.concatenate(blocks, axis=axis)
    return C.t_all_gather(out, axes, axis=axis, tiled=True)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_allreduce(x, w, axes, axis=0):
    """psum(x @ w) with the reduce half hidden behind the GEMM
    (matmul_rs ring + tiled all_gather). Backward keeps the Megatron
    psum/identity pairing of _mp_allreduce: d(x)/d(w) are LOCAL GEMMs,
    no collective (mp_ops.py psum_identity_bwd)."""
    return _matmul_allreduce_impl(x, w, axes, axis)


def _matmul_ar_fwd(x, w, axes, axis):
    return _matmul_allreduce_impl(x, w, axes, axis), (x, w)


def _matmul_ar_bwd(axes, axis, res, g):
    x, w = res
    return _mm(g, w.T), _tdot(x, g)


# fwd's rs-ring + tiled all_gather COMPOSE a full allreduce, so this is
# the Megatron psum/identity pairing (mp_ops.psum_identity_bwd): the
# cotangent is replicated over mp and the correct bwd is local GEMMs
# with zero collectives — an empty bwd ledger is the contract here
# tpulint: disable=vjp-ledger-symmetry
matmul_allreduce.defvjp(_matmul_ar_fwd, _matmul_ar_bwd)


def _matmul_gather_body(x, w, axes, nchunks):
    rows = x.shape[0]
    c = rows // nchunks
    parts = []
    for j in range(nchunks):
        xj = lax.slice_in_dim(x, j * c, (j + 1) * c, axis=0)
        parts.append(C.t_all_gather(_mm(xj, w), axes,
                                     axis=xj.ndim - 1, tiled=True))
    return jnp.concatenate(parts, axis=0) if nchunks > 1 else parts[0]


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_gather(x, w, axes, nchunks=1):
    """all_gather(x @ w, axis=-1, tiled) with the GEMM chunked over
    rows: chunk j's feature gather has no dependence on chunk j+1's
    matmul, so the gather pipelines behind the remaining compute.
    Backward keeps the _c_concat pairing (local slice, no collective)."""
    return _matmul_gather_impl(x, w, axes, nchunks)


def _matmul_gather_fwd(x, w, axes, nchunks):
    return _matmul_gather_impl(x, w, axes, nchunks), (x, w)


def _matmul_gather_bwd(axes, nchunks, res, g):
    x, w = res
    local = w.shape[-1]
    idx = C.axis_index(axes)
    g_loc = lax.dynamic_slice_in_dim(g, idx * local, local, axis=g.ndim - 1)
    return _mm(g_loc, w.T), _tdot(x, g_loc)


matmul_gather.defvjp(_matmul_gather_fwd, _matmul_gather_bwd)


# -- MoE: dispatch-a2a + batched expert FFN + combine-a2a as one ring -----
#
# The unfused expert-parallel MoE middle is
#   all_to_all(expert_in) -> batched expert FFN -> all_to_all(out)
# and both all_to_alls are exposed: the FFN depends on the whole
# dispatched tensor and the combine depends on the whole FFN output.
# The ring below exchanges one destination-rank block per tick — at
# shift t each rank sends block (idx+t)%p of its dispatch tensor
# directly to its owner and runs the expert GEMMs on the block that
# just landed, so tick t+1's ppermute (a fresh slice of the input,
# no dependence on tick t's GEMM) and tick t's return ppermute both
# hide behind the MXU work. Reference knob:
# ``strategy.hybrid_configs["moe_configs"]["ep_async_dispatch"]``.

def moe_overlap_enabled() -> bool:
    """The ep_async_dispatch knob, read live from the fleet strategy."""
    from . import fleet as _fleet

    strat = _fleet.get_strategy()
    if strat is None:
        return False
    moe_cfg = strat.hybrid_configs.get("moe_configs") or {}
    return bool(moe_cfg.get("ep_async_dispatch", False))


def moe_overlap_available(axes) -> bool:
    """True when the fused MoE ring may run: knob on, inside an SPMD
    region, over exactly one mesh axis (the expert-dim chunking is
    guaranteed by MoELayer's num_experts % ep check)."""
    return (moe_overlap_enabled() and C.in_spmd_region()
            and _ring_axis(axes) is not None)


def _chunk_ffn(blk, w1, b1, w2, b2, act):
    """Batched per-expert FFN on one ring block [eloc, C, d]."""
    dt = blk.dtype
    h = act(jnp.einsum("ecd,edf->ecf", blk, w1)
            + b1[:, None, :].astype(dt))
    return jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :].astype(dt)


def _moe_ring_body(x, w1, b1, w2, b2, axes, act, save_blocks):
    """The shared fwd ring. x: [E_total, C, d] (block j = the C slots
    destined for rank j's experts); w1/b1/w2/b2 are this rank's expert
    shards [eloc, ...]. Returns the combined [E_total, C, d] (block j =
    rank j's expert outputs for OUR tokens) and, when ``save_blocks``,
    the received dispatch blocks in tick order (the bwd residuals)."""
    name, p, idx = _ring_info(axes)
    eloc = x.shape[0] // p
    out = jnp.zeros_like(x)
    blocks = []
    for t in range(p):
        j = (idx + t) % p
        blk = lax.dynamic_slice_in_dim(x, j * eloc, eloc, axis=0)
        if t:
            # send block (i+t) to rank i+t <=> receive rank (i-t)'s
            # tokens for our experts
            blk = C.t_ppermute(blk, name,
                               [(s, (s + t) % p) for s in range(p)])
        if save_blocks:
            blocks.append(blk)
        o = _chunk_ffn(blk, w1, b1, w2, b2, act)
        if t:
            # return the processed block to its token-owner rank
            o = C.t_ppermute(o, name,
                             [(s, (s - t) % p) for s in range(p)])
        out = lax.dynamic_update_slice_in_dim(out, o, j * eloc, axis=0)
    return out, blocks


def _moe_a2a_ffn_impl(x, w1, b1, w2, b2, axes, act, save_blocks=False):
    from ..observability import annotate as _annotate

    with _annotate("moe_a2a_ffn_ring"):
        return _moe_ring_body(x, w1, b1, w2, b2, axes, act, save_blocks)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def moe_a2a_ffn(x, w1, b1, w2, b2, axes, act):
    """combine_a2a(expert_ffn(dispatch_a2a(x))) as one overlapped ring.

    Exactly the unfused ``t_all_to_all(0,1) -> FFN -> t_all_to_all(1,0)``
    math (concat order inside an expert's slot dim is irrelevant: the
    FFN acts per (expert, slot) row), with the ICI exchange chunked so
    it hides behind the expert GEMMs.
    """
    return _moe_a2a_ffn_impl(x, w1, b1, w2, b2, axes, act)[0]


def _moe_a2a_ffn_fwd(x, w1, b1, w2, b2, axes, act):
    out, blocks = _moe_a2a_ffn_impl(x, w1, b1, w2, b2, axes, act,
                                    save_blocks=True)
    return out, (jnp.stack(blocks), w1, b1, w2, b2)


def _moe_a2a_ffn_bwd(axes, act, res, g):
    """Mirrored ring: the cotangent of the combine a2a is dispatch-
    shaped and vice versa, so dL/dout blocks travel token-owner ->
    expert-owner (forward's dispatch direction), the per-block dFFN
    runs against the saved dispatch blocks, and dL/dx blocks return on
    the combine direction. Expert weight grads accumulate locally —
    each rank owns its expert shard and saw every token routed to it,
    so no cross-ring reduction is needed."""
    blocks, w1, b1, w2, b2 = res
    name, p, idx = _ring_info(axes)
    eloc = g.shape[0] // p
    dx = jnp.zeros_like(g)
    dw1 = jnp.zeros_like(w1)
    db1 = jnp.zeros_like(b1)
    dw2 = jnp.zeros_like(w2)
    db2 = jnp.zeros_like(b2)

    def ffn(blk, a1, c1, a2, c2):
        return _chunk_ffn(blk, a1, c1, a2, c2, act)

    for t in range(p):
        j = (idx + t) % p
        gblk = lax.dynamic_slice_in_dim(g, j * eloc, eloc, axis=0)
        if t:
            gblk = C.t_ppermute(gblk, name,
                                [(s, (s + t) % p) for s in range(p)])
        _, pull = jax.vjp(ffn, blocks[t], w1, b1, w2, b2)
        dblk, dw1_t, db1_t, dw2_t, db2_t = pull(gblk)
        dw1 = dw1 + dw1_t
        db1 = db1 + db1_t
        dw2 = dw2 + dw2_t
        db2 = db2 + db2_t
        if t:
            dblk = C.t_ppermute(dblk, name,
                                [(s, (s - t) % p) for s in range(p)])
        dx = lax.dynamic_update_slice_in_dim(dx, dblk, j * eloc, axis=0)
    return dx, dw1, db1, dw2, db2


moe_a2a_ffn.defvjp(_moe_a2a_ffn_fwd, _moe_a2a_ffn_bwd)


# -- Tensor-level fused linears (tape + pure-transform dual path) ---------
#
# Like the mp_ops primitives, each fused linear works under BOTH autodiff
# regimes: the eager tape (`loss.backward()` inside the engine's compiled
# step) via a recorded custom node, and pure function transforms
# (`jax.vjp` in the pipeline schedule / jit.to_static) via the
# custom_vjp on the value-level op above.

def _record_fused(name, out_val, bwd_fn, x: Tensor, weight: Tensor):
    sg = x.stop_gradient and weight.stop_gradient
    out = Tensor(out_val, stop_gradient=sg)
    if _engine.is_grad_enabled() and not sg:
        out.stop_gradient = False
        _engine.record_custom(name, bwd_fn, [x, weight], [out], out_val)
    return out


def _add_bias(out: Tensor, bias: Optional[Tensor]) -> Tensor:
    return out if bias is None else out + bias


def linear_ag_matmul(x: Tensor, weight: Tensor, bias: Optional[Tensor],
                     axes, axis: int) -> Tensor:
    """F.linear(all_gather(x, axis), weight, bias), overlapped."""
    xv, wv = x._value, weight._value

    def bwd(g):
        return _ag_matmul_bwd(axes, axis, (xv, wv), g)

    out = _record_fused("ag_matmul", ag_matmul(xv, wv, axes, axis), bwd,
                        x, weight)
    return _add_bias(out, bias)


def linear_matmul_rs(x: Tensor, weight: Tensor, bias: Optional[Tensor],
                     axes, axis: int) -> Tensor:
    """reduce_scatter(F.linear(x, weight), axis) + bias, overlapped."""
    xv, wv = x._value, weight._value

    def bwd(g):
        return _matmul_rs_bwd(axes, axis, (xv, wv), g)

    out = _record_fused("matmul_rs", matmul_rs(xv, wv, axes, axis), bwd,
                        x, weight)
    return _add_bias(out, bias)


def linear_matmul_allreduce(x: Tensor, weight: Tensor,
                            bias: Optional[Tensor], axes,
                            axis: int) -> Tensor:
    """allreduce(F.linear(x, weight)) + bias, reduce half overlapped."""
    xv, wv = x._value, weight._value

    def bwd(g):
        return _matmul_ar_bwd(axes, axis, (xv, wv), g)

    out = _record_fused("matmul_allreduce",
                        matmul_allreduce(xv, wv, axes, axis), bwd, x, weight)
    return _add_bias(out, bias)


def linear_matmul_gather(x: Tensor, weight: Tensor, bias: Optional[Tensor],
                         axes, nchunks: int) -> Tensor:
    """all_gather(F.linear(x, weight), axis=-1) chunk-pipelined.

    NOTE bias ordering: the unfused column layer adds its mp-sharded
    bias BEFORE the gather; here the gathered bias must be added after
    — callers pass a FULL (gathered) bias or None.
    """
    xv, wv = x._value, weight._value

    def bwd(g):
        return _matmul_gather_bwd(axes, nchunks, (xv, wv), g)

    out = _record_fused("matmul_gather",
                        matmul_gather(xv, wv, axes, nchunks), bwd, x, weight)
    return _add_bias(out, bias)


def pick_scatter_axis(shape: Sequence[int], axes) -> Optional[int]:
    """First leading (non-feature) dim the ring size divides, or None —
    the chunk-doesn't-divide unfused fallback."""
    name = _ring_axis(axes)
    if name is None:
        return None
    p = C.axis_size(name)
    for d in range(max(len(shape) - 1, 1)):
        if shape[d] % p == 0 and shape[d] >= p:
            return d
    return None


def scatter_divides(n: int, axes) -> bool:
    """True when the ring size divides ``n`` (matmul_rs needs the
    scattered dim chunkable; otherwise unfused fallback)."""
    name = _ring_axis(axes)
    return name is not None and n % C.axis_size(name) == 0


def chunk_count(rows: int, axes) -> int:
    """Largest chunk count <= ring size that divides ``rows`` (1 =
    nothing to pipeline -> callers fall back unfused)."""
    name = _ring_axis(axes)
    p = C.axis_size(name)
    for c in range(min(p, rows), 0, -1):
        if rows % c == 0:
            return c
    return 1
