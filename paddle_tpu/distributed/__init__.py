"""Distributed training (paddle.distributed analog).

TPU-native design (see SURVEY.md §2.3/§2.4/§7): the mesh-and-collectives
layer replaces ProcessGroupNCCL — communication lowers to XLA collectives
over ICI via jax.shard_map axis names; the Fleet hybrid-parallel surface
(topology, TP layers, sharding, PP, MoE) is preserved on top.
"""
from . import collective  # noqa: F401
from . import runtime  # noqa: F401
from .collective import (  # noqa: F401
    all_gather, all_gather_object, all_reduce, all_to_all, barrier,
    broadcast, broadcast_object_list, gather_object, get_group,
    get_rank, get_world_size, in_spmd_region, init_parallel_env, irecv,
    isend, new_group, recv, reduce, reduce_scatter, scatter, send,
    spmd_region, ReduceOp, Group, ProcessGroup, split_group)
from . import auto_parallel  # noqa: F401
from . import checkpoint  # noqa: F401
from . import fleet  # noqa: F401
from .auto_parallel import (Partial, ProcessMesh, Replicate, Shard,  # noqa: F401
                            dtensor_from_fn, reshard, shard_layer,
                            shard_tensor)
from . import sharding  # noqa: F401
from . import rpc  # noqa: F401
from . import stream  # noqa: F401
from .collective import P2POp, batch_isend_irecv  # noqa: F401
from . import utils  # noqa: F401
from .engine import ParallelEngine, bind_params, shard_module_params  # noqa: F401
from .parallel import DataParallel, ParallelEnv  # noqa: F401
from .store import TCPStore, create_or_get_global_tcp_store  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401

__all__ = [
    "all_gather", "all_reduce", "all_to_all", "barrier", "broadcast",
    "get_group", "get_rank", "get_world_size", "init_parallel_env",
    "new_group", "recv", "reduce", "reduce_scatter", "scatter", "send",
    "isend", "irecv", "ReduceOp", "Group", "ProcessGroup", "fleet",
    "stream", "P2POp", "batch_isend_irecv",
    "DataParallel", "ParallelEnv", "spmd_region", "in_spmd_region",
    "split_group", "sharding", "group_sharded_parallel",
    "save_group_sharded_model",
]

# API tail (aliases, semi-auto helpers, gated PS-era entries)
from .compat import *  # noqa: F401,F403,E402
from . import launch  # noqa: F401,E402
from . import checkpoint as io  # noqa: F401,E402  (paddle.distributed.io analog)
