"""ParallelEngine: compiles an eager model + optimizer into ONE sharded
XLA train step over the hybrid mesh.

This is the TPU-native replacement for the reference's per-op dispatch
inside `fleet.distributed_model` training loops (reference call stack:
SURVEY.md §3.3 — Python-driven 1F1B + eager NCCL ops). Instead of
host-dispatching thousands of ops per step, the engine traces the whole
forward + tape-backward + fused optimizer update under
``jax.shard_map`` over the ``HybridCommunicateGroup`` mesh, so:

- every mp/dp/sharding/pp collective lowers to an XLA collective on ICI,
- XLA fuses/overlaps compute and comm (the reference does this by hand
  with comm streams + hooks, reducer.cc / sharding overlap),
- parameters live as global ``jax.Array``s physically sharded per their
  ``dist_attr`` PartitionSpec (set by the mpu/sharded layers), and the
  step donates them (buffer aliasing → ZeRO-style memory behavior).

The eager tape (autograd/engine.py) records on tracers, so
``loss.backward()`` inside the traced step emits the backward into the
same XLA program — the mechanism the reference approximates with
jit.to_static + PIR interpreter (SURVEY.md §3.4).
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collective as C
from . import failpoints as _fp
from ..autograd import engine as _ad
from ..core import rng as _rng
from ..core.compile_stats import CompileStats
from ..observability import commledger as _cl
from ..observability import flops as _flops
from ..observability import goodput as _gp
from ..observability import healthmon as _hm
from ..observability import memledger as _ml
from ..observability import moestats as _moestats
from ..observability.catalog import train_metrics as _train_metrics
from ..tensor import Tensor

try:
    from jax import shard_map as _shard_map_mod  # jax >= 0.8

    def _shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_rep)
except Exception:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _sm

    def _shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_rep)

__all__ = ["ParallelEngine", "bind_params", "param_spec", "shard_module_params"]

# axes the token batch is sharded over. 'ep' rides here too: expert
# parallelism subdivides the data-parallel replicas (GShard/DeepSpeed-MoE
# deployment) — each ep rank sees its own token shard, MoE expert params
# shard over 'ep' (distinct per-rank grads, so ZeRO leaves them out and
# the grad mean skips the axis exactly like experts-over-dp).
_DATA_AXES = ("dp", "sharding", "ep")


def param_spec(p) -> P:
    """The PartitionSpec a tensor is sharded with (replicated default)."""
    da = getattr(p, "dist_attr", None)
    return da if isinstance(da, P) else P()


def _with_axis(spec: P, ndim: int, dim: int, axis: str) -> P:
    """``spec`` with mesh ``axis`` added as the sharding of dim ``dim``."""
    parts = list(spec) + [None] * (ndim - len(spec))
    parts[dim] = axis
    return P(*parts)


class _ZeroPlan:
    """ZeRO param/state sharding plan over the 'sharding' mesh axis.

    The reference partitions params greedily by size and hand-codes the
    reduce-scatter/broadcast traffic (dygraph_sharding_optimizer.py:224,
    group_sharded_stage3.py). Here the plan is declarative: each eligible
    parameter gets a shard *dim* (first dim divisible by the sharding
    degree and not already sharded by tp/pp), and the engine emits
    all_gather / psum_scatter on that dim inside the compiled step —
    XLA schedules and overlaps the traffic on ICI.

    Stage 1/2 ("os"/"os_g"): optimizer states (and the update math) are
    sharded; params stay replicated across 'sharding'.
    Stage 3 ("p_g_os"):   params are *stored* sharded and all-gathered
    just-in-time at forward entry (donated buffers keep persistent
    memory at shard size — per-device model-state bytes land at
    1/sharding_degree exactly, the memledger closed form). Selected by
    ``sharding_configs["sharding_stage"] = 3`` (the strategy surface),
    the per-param ``_zero3`` marker (group_sharded_parallel "p_g_os"),
    or quant_comm's param_gather (see ``store_sharded`` below). The
    gather runs through the comm_overlap bucket plan when one exists
    (grad_buckets.BucketPlan.gather — coalesced per signature bucket,
    the stacked-params seam as a scan_trips-exact lax.scan), else per
    parameter; the grads keep flowing through EXACTLY the stage-2
    reduce-scatter path, which is what makes stage-3 loss/params
    bit-match stage-2 (pinned by tests/bench).

    ``row_dims`` (the per-bucket ZeRO plan): {id(param): k} marking k
    leading stacked-layer dims the shard-dim search must skip — set
    when comm_overlap buckets the grad sync along the pp stacked-params
    seam (distributed/grad_buckets.py), so the reduce-scatter dim never
    collides with the layer-row axis the bucket scan chunks over. Only
    WHERE states shard moves; the update math is unchanged.

    ``store_sharded``: store EVERY plan entry's param sharded and
    all-gather at step entry (the stage-3 storage discipline) even at
    stage 1/2. Set when quant_comm's ``param_gather`` compresses the
    gather wire: the authoritative state must be the exact per-rank
    shard — a quantized post-update gather would otherwise either bake
    compression noise into the weights or leave device-divergent
    "replicated" copies that can't checkpoint (quant_comm.py
    quantized_param_gather docstring).
    """

    def __init__(self, mesh: Mesh, trainable, optimizer, row_dims=None,
                 store_sharded: bool = False):
        axis = getattr(optimizer, "state_partition_axis", None) \
            if optimizer is not None else None
        stage3 = any(getattr(p, "_zero3", False) for p in trainable)
        if (stage3 or store_sharded) and axis is None:
            axis = "sharding"
        self.axis = axis
        self.n = (mesh.shape[axis]
                  if axis is not None and axis in mesh.axis_names else 1)
        self.entries = {}
        if self.n <= 1:
            self.axis = None
            return
        for p in trainable:
            spec = param_spec(p)
            flat_spec = set()
            for ax in spec:
                flat_spec.update(ax if isinstance(ax, (tuple, list))
                                 else (ax,))
            # params already sharded over a data axis (MoE experts over dp)
            # have per-rank-distinct grads; the ZeRO scatter math below
            # assumes replicated grads, so leave them out of the plan
            if flat_spec & set(_DATA_AXES):
                continue
            shape = tuple(p._value.shape)
            start = (row_dims or {}).get(id(p), 0)
            for d in range(start, len(shape)):
                used = spec[d] if d < len(spec) else None
                if used is None and shape[d] % self.n == 0 \
                        and shape[d] >= self.n:
                    self.entries[id(p)] = (
                        d, getattr(p, "_zero3", False) or store_sharded)
                    break

    def entry(self, p):
        return self.entries.get(id(p)) if self.axis else None

    def state_spec(self, p) -> P:
        e = self.entry(p)
        if e is None:
            return param_spec(p)
        return _with_axis(param_spec(p), p._value.ndim, e[0], self.axis)

    def storage_spec(self, p) -> P:
        e = self.entry(p)
        if e is None or not e[1]:
            return param_spec(p)
        return _with_axis(param_spec(p), p._value.ndim, e[0], self.axis)


@contextlib.contextmanager
def bind_params(params: Sequence, values: Sequence):
    """Temporarily swap each Parameter's backing array (functional call).

    The analog of functorch-style functional_call; lets one model object
    serve both the eager path and the traced SPMD step.
    """
    saved = [p._value for p in params]
    saved_nodes = [(p._grad_node, p.grad) for p in params]
    try:
        for p, v in zip(params, values):
            p._value = v
            p._grad_node = None
            p.grad = None
        yield
    finally:
        for p, v, (n, g) in zip(params, saved, saved_nodes):
            p._value = v
            p._grad_node = n
            p.grad = g


def _mesh_data_axes(mesh: Mesh):
    return tuple(a for a in _DATA_AXES
                 if a in mesh.axis_names and mesh.shape[a] > 1)


def _batch_tokens(leaf_vals) -> int:
    """Tokens one (host-local) batch carries: the largest integer leaf
    (token ids [B, S] beat labels [B]); falls back to the leading dim of
    the first leaf (samples) for non-token workloads like vision."""
    tok = 0
    for v in leaf_vals:
        if getattr(v, "ndim", 0) >= 1 and \
                jnp.issubdtype(v.dtype, jnp.integer):
            tok = max(tok, int(np.prod(v.shape)))
    if tok == 0 and leaf_vals:
        v0 = leaf_vals[0]
        tok = int(v0.shape[0]) if getattr(v0, "ndim", 0) >= 1 else 1
    return tok


def _multiprocess(mesh: Mesh) -> bool:
    return jax.process_count() > 1


def global_put(value, mesh: Mesh, spec: P):
    """Place a host-replicated value as a global array sharded by spec.

    Single-process: plain device_put. Multi-process: every process holds
    the FULL value (deterministic init); each contributes its addressable
    shards (reference analog: broadcast_mp_parameters — here no traffic,
    the copy is local because the host already has the bytes).
    """
    sh = NamedSharding(mesh, spec)
    if not _multiprocess(mesh):
        return jax.device_put(value, sh)
    np_val = np.asarray(value)
    return jax.make_array_from_callback(np_val.shape, sh,
                                        lambda idx: np_val[idx])


def _globalize_batch(leaf_vals, b_specs, mesh: Mesh):
    """Multi-process: each process feeds its LOCAL batch (the
    DistributedBatchSampler contract); assemble global arrays whose
    data-axis shards are the per-process pieces."""
    if not _multiprocess(mesh):
        return leaf_vals
    from jax.experimental import multihost_utils as mh

    out = []
    for v, spec in zip(leaf_vals, b_specs):
        if spec == P() or all(s is None for s in spec):
            out.append(global_put(v, mesh, spec))
        else:
            out.append(mh.host_local_array_to_global_array(
                np.asarray(v), mesh, spec))
    return tuple(out)


def materialize_lazy_params(model, mesh: Optional[Mesh] = None,
                            spec_fn=None, seed: int = 0):
    """Materialize LazyGuard-built parameters directly at their sharding.

    Each parameter's windows are generated by the keyed shard-local
    initializer path (nn/initializer.py _generate_window): a process
    only ever materializes its addressable shards, so host+device bytes
    are O(shard) — the scalable replacement for full-host init +
    global_put (reference rank-0 broadcast:
    fleet/utils/hybrid_parallel_util.py:213). Deterministic in
    (seed, qualified parameter name, window offsets) — identical across
    processes with no communication.
    """
    import zlib

    from ..framework.lazy_init import LazySpec
    from ..nn.initializer import _generate_window

    base = jax.random.PRNGKey(seed)
    for name, p in model.named_parameters():
        lz = p._value
        if not isinstance(lz, LazySpec):
            continue
        key = jax.random.fold_in(base, zlib.crc32(name.encode()))
        shape, dtype, init = lz.shape, lz.dtype, lz.init
        if mesh is None:
            window = tuple(slice(0, s) for s in shape)
            p._value = _generate_window(init, shape, window, dtype, key)
            continue
        spec = spec_fn(p) if spec_fn is not None else param_spec(p)
        sh = NamedSharding(mesh, spec)

        def cb(idx, init=init, shape=shape, dtype=dtype, key=key):
            return np.asarray(_generate_window(init, shape, idx, dtype,
                                               key))

        p._value = jax.make_array_from_callback(shape, sh, cb)
    return model


def shard_module_params(model, mesh: Mesh):
    """Physically shard every parameter per its dist_attr (global arrays)."""
    materialize_lazy_params(model, mesh)
    for p in model.parameters():
        p._value = global_put(p._value, mesh, param_spec(p))
    return model


class ParallelEngine:
    """Compile model+optimizer into a donated, sharded train step.

    Usage::

        hcg = fleet.init(strategy)           # builds the hybrid mesh
        eng = ParallelEngine(model, opt, hcg.mesh)
        step = eng.train_step(lambda model, batch:
                              loss_fn(model(batch["x"]), batch["y"]))
        loss = step({"x": xb, "y": yb})      # one XLA execution
    """

    def __init__(self, model, optimizer=None, mesh: Optional[Mesh] = None,
                 comm_overlap: Optional[bool] = None,
                 comm_buffer_size_mb: Optional[float] = None,
                 mem_ledger: Optional[bool] = None,
                 quant_comm=None, sharding_stage: Optional[int] = None,
                 stage3_release_after_forward: Optional[bool] = None,
                 offload=None):
        import os

        from . import grad_buckets as _gb
        from . import host_offload as _ho
        from . import quant_comm as _qc

        self.model = model
        self.optimizer = optimizer
        if mesh is None:
            from . import fleet as _fleet

            hcg = _fleet.get_hybrid_communicate_group()
            mesh = hcg.mesh if hcg is not None else C.get_world_mesh()
        if mesh is None:
            C.init_parallel_env()
            mesh = C.get_world_mesh()
        self.mesh = mesh
        self.params: List = list(model.parameters())
        self.trainable: List = [p for p in self.params if p.trainable]
        self._seed = 0
        self._mesh_epoch = C.mesh_epoch()
        self._compiled: Dict[Any, Callable] = {}
        # compile-cache telemetry (same counters as the serving path):
        # a healthy train loop compiles each (shape, spec) signature
        # once and shows only cache hits in steady state — regressions
        # that force recompiles (e.g. an overlap path keyed on a traced
        # shape) surface here and on the bench JSON lines
        self.stats = CompileStats()
        # unified telemetry (observability/): per-step wall time, loss,
        # grad-norm, tokens/s, MFU, device memory, compile counters —
        # all host-side on fetched scalars, never inside the trace
        self._metrics = _train_metrics()
        # run-health watcher (observability/healthmon): rolling robust
        # spike/stall detection over the scalars the lagged fetch below
        # already pays for. PER-ENGINE windows — a fresh model's first
        # loss must never be judged against another run's converged
        # baseline — surfaced on /healthz via a weakref provider
        self._health = _hm.HealthMonitor()
        self._health.register_healthz("train_health")
        self._n_params_cfg = _flops.params_from_config(
            getattr(model, "config", None))
        self._stats_reported = (0, 0)    # (compiles, cache_hits) synced
        self._pending_scalars = None     # (loss_dev, gnorm_dev) lazy
        self._pending_found = None       # scaler found_inf of that step
        self._pending_moe = None         # MoE stats devices, same lag
        self._prev_step_entry = None
        # per-program static comm ledgers (observability/commledger):
        # filled when a program first traces, re-published every step
        self._ledgers: Dict[Any, Any] = {}
        self._last_key = None
        # per-program HBM memory ledgers (observability/memledger):
        # XLA memory_analysis of the SAME program, stored next to the
        # comm ledger. Analysis costs one extra trace + AOT compile
        # per program, so it is eager only behind the knob (ctor arg
        # or PADDLE_TPU_MEM_LEDGER=1); memory_ledger() computes on
        # demand either way from the per-key example args kept below.
        self._mem_on = (bool(int(os.environ.get(
            "PADDLE_TPU_MEM_LEDGER", "0") or 0))
            if mem_ledger is None else bool(mem_ledger))
        self._mem_ledgers: Dict[Any, Any] = {}
        self._mem_args: Dict[Any, Any] = {}
        # durable metrics time-series journal (observability/timeseries):
        # a background sampler snapshots the registry into
        # <dir>/metrics.jsonl every PADDLE_TPU_TIMESERIES_S seconds.
        # Pure host-side file IO on an existing snapshot — adds zero ops
        # to compiled programs, so compile caches stay flat.
        self.sampler = None
        ts_dir = os.environ.get("PADDLE_TPU_TIMESERIES_DIR")
        if ts_dir:
            from ..observability import timeseries as _ts
            try:
                self.sampler = _ts.attach_dir(ts_dir, interval_s=float(
                    os.environ.get("PADDLE_TPU_TIMESERIES_S", "5.0")))
            except (OSError, ValueError):
                self.sampler = None
        self._state_acct = None          # cached StateAccounting
        self._live_peak = 0              # live-bytes high-water mark
        self._last_tokens = 0
        self._last_step_seconds = 0.0
        self._last_dispatch_fresh = False
        # set by restore_checkpoint, cleared after the next dispatch:
        # the first execution after a cross-process restore can pay a
        # silent XLA-level relayout/recompile (loaded arrays' layouts
        # differ from compiled-step outputs) that the host-side key
        # cache never sees — goodput books that dispatch as compile
        # (warmup), and the health monitor's step-time baseline skips it
        self._post_restore_warmup = False
        # profile_exposed_comm() replays: suppress telemetry/counters
        # so offline attribution never pollutes the live metrics
        self._profiling = False
        # T3-style bucketed grad sync (distributed/grad_buckets.py):
        # knob from strategy.hybrid_configs["sharding_configs"], or the
        # explicit constructor override (tests / engines built without
        # fleet.init). Default off — the unbucketed tail sync.
        cfg_on, cfg_mb = _gb.strategy_config()
        self._overlap_on = bool(cfg_on if comm_overlap is None
                                else comm_overlap)
        self._overlap_mb = float(cfg_mb if comm_buffer_size_mb is None
                                 else comm_buffer_size_mb)
        # the pp stacked-params chunk seam: the natural bucketing grain
        # for pipelined models (PipelineLayer.grad_bucket_seam)
        self._seam_row_dims = None
        seam_fn = getattr(model, "grad_bucket_seam", None)
        if self._overlap_on and callable(seam_fn):
            self._seam_row_dims = {id(p): int(k) for p, k in seam_fn()}
        self._bucket_plan = None
        # quantized collectives (distributed/quant_comm.py): the
        # strategy.hybrid_configs["quant_comm"] sub-config, or the
        # explicit constructor override (a dict or QuantConfig). The
        # grad_sync half rides the comm_overlap bucket plan; the
        # mp_rings half is read by collective_matmul from the fleet
        # strategy directly.
        self._quant_cfg = (_qc.strategy_config() if quant_comm is None
                           else _qc.make_config(quant_comm))
        # per-bucket error-feedback residuals: f32 global arrays,
        # rank-distinct (dim 0 sharded over every mesh axis), created
        # lazily by _ensure_quant_state once the bucket plan exists and
        # carried through the compiled step as donated train state
        self._quant_residuals: Dict[str, Any] = {}
        self._quant_specs: Dict[str, P] = {}
        self._pending_qnorm = None
        # ZeRO sharding stage (distributed_strategy sharding_configs,
        # or the explicit constructor override): stage 3 stores every
        # plan entry's param shard-only and gathers just-in-time at
        # forward entry; stage3_release_after_forward picks the gather
        # grain (True = per signature bucket / seam scan through the
        # comm_overlap plan, False = per-parameter entry wave). Both
        # are exact data movement — same bytes on the wire, same
        # values, different node granularity.
        cfg_stage, cfg_rel = _gb.stage_config()
        self._sharding_stage = int(cfg_stage if sharding_stage is None
                                   else sharding_stage)
        self._stage3_release = bool(
            cfg_rel if stage3_release_after_forward is None
            else stage3_release_after_forward)
        self._zero = _ZeroPlan(
            mesh, self.trainable, optimizer,
            row_dims=self._seam_row_dims if self._overlap_on else None,
            store_sharded=bool(self._quant_cfg.enabled
                               and self._quant_cfg.param_gather)
            or self._sharding_stage >= 3)
        # host-memory offload tier (distributed/host_offload.py): the
        # strategy sharding_configs["offload"] sub-config, or the
        # explicit constructor override. When active, optimizer moments
        # / AMP masters / EF residuals (optionally stored param shards)
        # live on the host between steps and are prefetched per
        # signature bucket at dispatch — bit-exact, ledger-booked.
        self._offload = _ho.make_tier(
            offload if offload is not None else _ho.offload_config(),
            mesh)
        # LazyGuard-built params materialize straight into their (zero3-
        # aware) storage sharding: O(shard) bytes per process, no full-
        # size init anywhere
        materialize_lazy_params(model, mesh,
                                spec_fn=self._zero.storage_spec)
        for p in self.params:
            p._value = global_put(p._value, mesh, self._zero.storage_spec(p))

    # -- optimizer state management -------------------------------------
    def _ensure_opt_states(self):
        from . import host_offload as _ho

        opt = self.optimizer
        shapes = opt._state_shapes()
        states = []
        for p in self.trainable:
            st = opt._param_state(p, shapes)
            spec = self._zero.state_spec(p)
            # host-tier entries (HostState) already carry their live
            # sharding and re-place through the offload tier, never a
            # fresh global_put
            st = {k: global_put(v, self.mesh, spec)
                  if not _ho.is_host(v)
                  and v.shape == tuple(p._value.shape)
                  else v for k, v in st.items()}
            opt._states[id(p)] = st
            states.append(st)
            mw = opt._master_weights.get(id(p))
            if mw is not None and not _ho.is_host(mw):
                opt._master_weights[id(p)] = global_put(mw, self.mesh, spec)
        return states

    # -- sync-signature helpers (shared by train_step + quant state) -----
    def _sync_axes_env(self):
        mesh = self.mesh
        data_axes = _mesh_data_axes(mesh)
        sep_axes = tuple(a for a in ("sep",) if a in mesh.axis_names
                         and mesh.shape[a] > 1)
        pp_axes = tuple(a for a in ("pp",)
                        if getattr(self.model, "_pp_ownership", False)
                        and a in mesh.axis_names and mesh.shape[a] > 1)
        return data_axes, data_axes + sep_axes, pp_axes

    def _param_spec_axes(self, p):
        spec_axes = set()
        for ax in param_spec(p):
            if isinstance(ax, (tuple, list)):
                spec_axes.update(ax)
            elif ax is not None:
                spec_axes.add(ax)
        return spec_axes

    def _param_grad_axes(self, p, pp_axes):
        spec_axes = self._param_spec_axes(p)
        extra = tuple(a for a in pp_axes if a not in spec_axes)
        # sequence-parallel replicated params (LayerNorm etc.) see only
        # a seq shard per mp rank: their grads must psum over mp
        # (reference sequence_parallel_utils.py:156 allreduce hooks)
        if getattr(p, "sequence_parallel", False):
            extra += tuple(
                a for a in ("mp",) if a in self.mesh.axis_names
                and self.mesh.shape[a] > 1 and a not in spec_axes)
        return extra

    def _build_bucket_plan(self):
        """The deterministic comm_overlap bucket plan (None when the
        knob is off or nothing buckets) — same construction train_step
        performs, callable standalone so restore_checkpoint can size
        the quantization residual buffers before any step traced."""
        if not self._overlap_on:
            return None
        from . import grad_buckets as _gb

        data_axes, gmean_axes, pp_axes = self._sync_axes_env()
        return _gb.build_plan(
            self.trainable, self.mesh, self._zero, gmean_axes,
            data_axes, self._param_spec_axes,
            lambda p: self._param_grad_axes(p, pp_axes), param_spec,
            seam_row_dims=self._seam_row_dims,
            buffer_mb=self._overlap_mb)

    def _quant_grad_cfg(self):
        """The active grad-sync quantization config, or None. Rides
        the comm_overlap bucket plan: quantizing an unbucketed tail
        sync is not supported (the bucket is the chunk-lattice grain —
        ISSUE/EQuARX), so knob-on without comm_overlap is full
        precision."""
        cfg = self._quant_cfg
        return cfg if (cfg is not None and cfg.enabled
                       and cfg.grad_sync and self._overlap_on) else None

    def _ensure_quant_state(self):
        """Create (once) the per-bucket error-feedback residual
        buffers: f32 zeros at the bucket payload size, dim 0 sharded
        over EVERY >1 mesh axis so each rank owns exactly its local
        residual (compression error is rank-local state — it
        checkpoints shard-exact and never reshards meaningfully, like
        the per-process RNG streams)."""
        qcfg = self._quant_grad_cfg()
        if qcfg is None or not qcfg.error_feedback:
            return
        plan = self._build_bucket_plan()
        if plan is None:
            return
        axes = tuple(a for a in self.mesh.axis_names
                     if self.mesh.shape[a] > 1)
        prod = 1
        for a in axes:
            prod *= int(self.mesh.shape[a])
        spec = P(axes) if axes else P()
        for name, lshape in plan.residual_shapes().items():
            self._quant_specs[name] = spec
            if name in self._quant_residuals:
                continue
            gshape = (int(lshape[0]) * prod,) + tuple(lshape[1:])
            self._quant_residuals[name] = global_put(
                np.zeros(gshape, np.float32), self.mesh, spec)

    # -- the compiled step ----------------------------------------------
    def train_step(self, fn: Callable, batch_specs=None,
                   donate: bool = True, scaler=None):
        """Build ``step(batch) -> loss`` running fwd+bwd+update as one
        sharded XLA program. ``fn(model, batch)`` must return a scalar
        loss Tensor.

        ``scaler``: an ``amp.GradScaler`` — when given, the whole dynamic
        loss-scaling protocol runs INSIDE the compiled step (reference:
        hybrid_parallel_gradscaler.py — found_inf allreduced over every
        parallel group; here a traced pmax over all mesh axes, with the
        scale/counters as carried device state and the param/state update
        where-guarded so an overflow step is a true no-op).
        """
        mesh = self.mesh
        # 'sep' (context parallel) splits the *sequence*: grads of
        # replicated params are per-block partials, so they average over
        # sep exactly like a batch split (but batch dims are NOT sharded
        # over sep — the model slices seq itself)
        data_axes, gmean_axes, pp_axes = self._sync_axes_env()
        opt = self.optimizer
        params, trainable = self.params, self.trainable
        t_index = [i for i, p in enumerate(params) if p.trainable]

        self._ensure_opt_states()
        zero = self._zero
        pspecs = tuple(zero.storage_spec(p) for p in params)
        sspecs = tuple({k: zero.state_spec(p)
                        if v.shape == tuple(p._value.shape) else P()
                        for k, v in opt._states[id(p)].items()}
                       for p in trainable)

        use_scaler = scaler is not None and scaler.is_enable()

        def _step(pvals, svals, mvals, qvals, batch, lr, stepc, seed,
                  amp_in):
            with C.spmd_region():
                if gmean_axes:
                    # distinct RNG stream per data-parallel/sep rank (mp/pp
                    # ranks share a stream: replicated tensors must drop
                    # identically; mp-sharded ones use 'local_seed')
                    seed = seed * jnp.uint32(1000003) + \
                        C.axis_index(gmean_axes).astype(jnp.uint32)
                ctx = _rng.fork_traced(seed)
                ctx.__enter__()
                try:
                    return _step_inner(pvals, svals, mvals, qvals,
                                       batch, lr, stepc, amp_in)
                finally:
                    ctx.__exit__(None, None, None)

        def _spec_axes(p):
            return self._param_spec_axes(p)

        def _grad_axes(p):
            return self._param_grad_axes(p, pp_axes)

        def _shard_of(p, v, dim):
            idx = lax.axis_index(zero.axis)
            loc = v.shape[dim] // zero.n
            return lax.dynamic_slice_in_dim(v, idx * loc, loc, axis=dim)

        # T3-style bucketed grad sync (grad_buckets.py): a static plan
        # over (signature groups x size-targeted buckets, the stacked-
        # params seam as a lax.scan) built HERE from shapes/specs only —
        # nothing shape-derived reaches a compile key, and knob-off
        # leaves the unbucketed path byte-for-byte untouched
        bucket_plan = self._build_bucket_plan()
        self._bucket_plan = bucket_plan
        # quantized grad sync (quant_comm): rides the bucket plan; the
        # error-feedback residuals are per-bucket donated train state
        # (created once — zeros — then carried step to step)
        qcfg = self._quant_grad_cfg() if bucket_plan is not None \
            else None
        self._ensure_quant_state()
        # offload adoption: page the freshly-ensured state classes out
        # to the host tier before the first dispatch (the first
        # prefetch_step brings them back bucket-by-bucket)
        if self._offload is not None:
            self._offload.page_out_step(self, spawn=False)
        qspecs = dict(self._quant_specs)
        # quantized ZeRO param all-gather (stage 2 post-update, stage 3
        # entry): int8 wire with each rank's own exact shard spliced
        # back, so the authoritative shard path never sees noise
        pg_cfg = (self._quant_cfg
                  if self._quant_cfg.enabled
                  and self._quant_cfg.param_gather else None)

        def _zero_gather(v, dim):
            if pg_cfg is not None:
                from . import quant_comm as _qc

                return _qc.quantized_param_gather(v, (zero.axis,), dim,
                                                  pg_cfg)
            return C.t_all_gather(v, zero.axis, axis=dim, tiled=True)

        # stage-3 stored-sharded params (store_sharded plan entries):
        # gathered just-in-time at forward entry. With a bucket plan
        # and the release knob on, the gather goes through the SAME
        # signature buckets the backward scatters grads through
        # (grad_buckets.BucketPlan.gather — coalesced flat all_gather
        # per bucket, the stacked seam as a scan_trips-exact lax.scan,
        # quantized wire + own-shard splice under quant_comm's
        # param_gather); otherwise one per-parameter gather wave. Both
        # are exact data movement, so the wire bytes and the resulting
        # values are identical — only the node granularity differs.
        s3_gather = [(i, zero.entry(p)[0]) for i, p in enumerate(params)
                     if zero.entry(p) is not None and zero.entry(p)[1]]
        s3_bucketed = bool(s3_gather) and bucket_plan is not None \
            and self._stage3_release

        def _step_inner(pvals, svals, mvals, qvals, batch, lr, stepc,
                        amp_in):
            # ZeRO-3 params arrive as shards: all-gather for the forward,
            # but keep the stored shard for the optimizer update
            pshards = pvals
            pvals = list(pvals)
            if s3_gather:
                gathered = {}
                if s3_bucketed:
                    gathered = bucket_plan.gather(
                        {id(params[i]): pvals[i] for i, _ in s3_gather},
                        qcfg=pg_cfg)
                for i, d in s3_gather:
                    pid = id(params[i])
                    pvals[i] = gathered[pid] if pid in gathered \
                        else _zero_gather(pvals[i], d)
            pvals = tuple(pvals)
            # MoE routing telemetry: collect the traced expert-load /
            # drop stats each MoELayer records during the forward, to be
            # returned as extra (replicated) step outputs. The pipelined
            # path is excluded — its stage-masked scan records values the
            # gauges would misreport (observability/moestats.py).
            collect_moe = not getattr(self.model, "_pp_ownership", False)
            with bind_params(params, pvals):
                t_batch = jax.tree_util.tree_map(
                    lambda v: Tensor(v, stop_gradient=True), batch)
                if collect_moe:
                    _moestats.begin()
                try:
                    loss = fn(self.model, t_batch)
                finally:
                    moe_recs = _moestats.drain() if collect_moe else []
                moe_tel = {}
                for li, st in enumerate(moe_recs):
                    load, routed = st["load"], st["routed"]
                    dropped, aux = st["dropped"], st["aux"]
                    if gmean_axes:
                        # token counts ADD over the batch-sharding axes
                        # (each rank routed its own token shard); the
                        # aux loss averages like the reported loss
                        load = C.t_psum(load, gmean_axes)
                        routed = C.t_psum(routed, gmean_axes)
                        dropped = C.t_psum(dropped, gmean_axes)
                        aux = C.t_pmean(aux, gmean_axes)
                    moe_tel[f"layer{li}"] = {
                        "load": load, "routed": routed,
                        "dropped": dropped, "aux": aux}
                if use_scaler:
                    scale_v, good_v, bad_v, tstep_v = amp_in
                    # cap the scale below the loss dtype's max so the
                    # backward seed can never itself overflow to inf
                    # (f16 max is 65504 — one doubling past the default
                    # 2^15 scale would cross it). Power-of-two cap keeps
                    # scale/unscale an exact mantissa-preserving round
                    # trip and leaves the default 2^15 init untouched.
                    ldt = loss._value.dtype
                    scale_cap = 2.0 ** 15 if ldt == jnp.float16 else 2.0 ** 62
                    scale_v = jnp.minimum(scale_v, jnp.float32(scale_cap))
                    # loss scaling = seeding the tape with `scale` instead
                    # of 1 (same grads as (loss*scale).backward(), one
                    # less op); the reported loss stays unscaled
                    loss.backward(Tensor(
                        scale_v.astype(loss._value.dtype),
                        stop_gradient=True))
                else:
                    loss.backward()
                raw_grads = {
                    id(p): (p.grad._value if p.grad is not None
                            else jnp.zeros_like(p._value))
                    for p in trainable}
                # comm_overlap: issue the per-bucket collectives (the
                # seam scan + the eager flat buckets) — bit-exact vs
                # the per-parameter path below (when quant_comm is off),
                # with the grad-norm sum-of-squares folded into the
                # bucket scan and the quantization error-feedback
                # residuals threaded through as train state
                if bucket_plan is not None:
                    bsync, bgsq, new_qr = bucket_plan.sync(
                        raw_grads, qcfg=qcfg, residuals=qvals)
                else:
                    bsync, bgsq, new_qr = {}, None, {}
                upd_in, grads = [], []
                for i, p in zip(t_index, trainable):
                    g = raw_grads[id(p)]
                    e = zero.entry(p)
                    if id(p) in bsync:
                        g = bsync[id(p)]
                        if e is not None:
                            upd_in.append(
                                mvals[i] if mvals and i in mvals
                                else (pshards[i] if e[1]
                                      else _shard_of(p, pvals[i], e[0])))
                        else:
                            upd_in.append(mvals[i] if mvals and i in mvals
                                          else pvals[i])
                    elif e is not None:
                        # grad mean over plain dp, then reduce-scatter the
                        # sharding axis onto the owner shard (ZeRO)
                        dim = e[0]
                        dp_only = tuple(a for a in gmean_axes
                                        if a != zero.axis)
                        if dp_only:
                            g = C.t_pmean(g, dp_only)
                        psum_axes = _grad_axes(p)
                        if psum_axes:
                            g = C.t_psum(g, psum_axes)
                        if zero.axis in data_axes:
                            g = C.t_psum_scatter(
                                g, zero.axis, scatter_dimension=dim,
                                tiled=True) / zero.n
                        else:
                            g = _shard_of(p, g, dim)
                        upd_in.append(mvals[i] if mvals and i in mvals
                                      else (pshards[i] if e[1]
                                            else _shard_of(p, pvals[i], dim)))
                    else:
                        # params sharded over a data axis (MoE experts over
                        # dp) already receive their cross-rank grad sum via
                        # the all_to_all transpose — no pmean over that
                        # axis, only the global-batch mean rescale
                        spec_axes = _spec_axes(p)
                        pm = tuple(a for a in gmean_axes
                                   if a not in spec_axes)
                        if pm:
                            g = C.t_pmean(g, pm)
                        dup = 1
                        for a in gmean_axes:
                            if a in spec_axes:
                                dup *= mesh.shape[a]
                        if dup > 1:
                            g = g / dup
                        psum_axes = _grad_axes(p)
                        if psum_axes:
                            g = C.t_psum(g, psum_axes)
                        upd_in.append(mvals[i] if mvals and i in mvals
                                      else pvals[i])
                    grads.append(g)
                amp_out = ()
                if use_scaler:
                    # traced found_inf, synced across EVERY parallel axis
                    # (the reference allreduces found_inf over mp/pp/
                    # sharding groups one by one; one pmax is equivalent)
                    finite = jnp.float32(1.0)
                    for g in grads:
                        finite = finite * jnp.all(
                            jnp.isfinite(g)).astype(jnp.float32)
                    found = 1.0 - finite
                    sync_axes = tuple(a for a in mesh.axis_names
                                      if mesh.shape[a] > 1)
                    if sync_axes:
                        found = C.t_pmax(found, sync_axes)
                    found_b = found > 0
                    # unscale in f32; zero overflowed grads so the (thrown
                    # away) update math stays NaN-free
                    inv = jnp.where(found_b, 0.0, 1.0 / scale_v)
                    grads = [(g.astype(jnp.float32) * inv).astype(g.dtype)
                             for g in grads]
                    # bias-correction step count advances only on applied
                    # steps (the reference skips optimizer.step entirely)
                    stepc = tstep_v + (1 - found.astype(jnp.int32))
                    # a skipped step must be a true no-op for the EF
                    # residuals too: they were updated from the scaled
                    # (possibly overflowed → NaN-decoding) grads, so
                    # roll them back exactly like params/moments
                    if new_qr:
                        new_qr = {k: jnp.where(found_b, qvals[k], v)
                                  for k, v in new_qr.items()}
                # global grad-norm (telemetry): local sum-of-squares,
                # psum'd over exactly the axes each grad is sharded on
                # (spec axes, + the ZeRO axis for scattered shards) so
                # replicated grads contribute once. Bucketed params
                # arrive pre-folded (one psum per signature group, the
                # seam contribution accumulated in the scan carry);
                # they were summed pre-unscale, so the scaler's inverse
                # applies squared (inv=0 on overflow matches the zeroed
                # per-param grads).
                gsq = jnp.float32(0.0)
                if bgsq is not None:
                    gsq = bgsq * (inv * inv if use_scaler
                                  else jnp.float32(1.0))
                for p, g in zip(trainable, grads):
                    if id(p) in bsync:
                        continue
                    loc = jnp.sum(jnp.square(g.astype(jnp.float32)))
                    axes_set = set(_spec_axes(p))
                    e = zero.entry(p)
                    if e is not None:
                        axes_set.add(zero.axis)
                    ax = tuple(a for a in axes_set
                               if a in mesh.axis_names
                               and mesh.shape[a] > 1)
                    if ax:
                        loc = C.t_psum(loc, ax)
                    gsq = gsq + loc
                gnorm = jnp.sqrt(gsq)
                new_p, new_s = opt._fused_update(
                    tuple(upd_in), tuple(grads), tuple(svals), lr, stepc)
                if use_scaler:
                    new_p = tuple(jnp.where(found_b, u, n)
                                  for u, n in zip(upd_in, new_p))
                    new_s = tuple(
                        {k: jnp.where(found_b, old[k], ns[k])
                         if hasattr(ns[k], "shape") else ns[k]
                         for k in ns}
                        for old, ns in zip(svals, new_s))
                    if scaler.is_use_dynamic_loss_scaling():
                        # dynamic loss-scale bookkeeping, pure arithmetic
                        bad1 = jnp.where(found_b, bad_v + 1, 0)
                        good1 = jnp.where(found_b, 0, good_v + 1)
                        dec = found_b & (bad1 >= scaler._decr_every)
                        scale1 = jnp.where(
                            dec,
                            jnp.maximum(scale_v * scaler._decr_ratio, 1.0),
                            scale_v)
                        bad2 = jnp.where(dec, 0, bad1)
                        inc = (~found_b) & (good1 >= scaler._incr_every)
                        scale2 = jnp.minimum(
                            jnp.where(inc, scale1 * scaler._incr_ratio,
                                      scale1),
                            jnp.float32(scale_cap))
                        good2 = jnp.where(inc, 0, good1)
                    else:  # static scale: counters track, scale is fixed
                        scale2 = scale_v
                        good2 = jnp.where(found_b, 0, good_v + 1)
                        bad2 = jnp.where(found_b, bad_v + 1, 0)
                    amp_out = (scale2, good2, bad2, stepc,
                               found.astype(jnp.float32))
                out_p = list(pvals)
                out_m = dict(mvals) if mvals else {}
                for i, p, nv in zip(t_index, trainable, new_p):
                    e = zero.entry(p)
                    if e is not None and not e[1]:
                        # stage 1/2: params stay replicated — gather the
                        # updated shards (the reference's param broadcast,
                        # dygraph_sharding_optimizer.py:317; quantized
                        # wire + own-shard splice behind quant_comm's
                        # param_gather knob)
                        nv_p = _zero_gather(nv, e[0])
                    else:
                        nv_p = nv
                    if out_m and i in out_m:
                        out_m[i] = nv
                        out_p[i] = nv_p.astype(pvals[i].dtype)
                    else:
                        out_p[i] = nv_p
                lv = loss._value
                all_axes = tuple(a for a in mesh.axis_names
                                 if mesh.shape[a] > 1)
                if all_axes:
                    lv = C.t_pmean(lv, all_axes)
                # quantization telemetry: global L2 of the carried EF
                # residuals (how much gradient signal is in flight in
                # the compensation state) — one scalar psum, only in
                # the quantized program
                qnorm = jnp.float32(0.0)
                if new_qr:
                    qsq = jnp.float32(0.0)
                    for v in new_qr.values():
                        qsq = qsq + jnp.sum(jnp.square(
                            v.astype(jnp.float32)))
                    if all_axes:
                        qsq = C.t_psum(qsq, all_axes)
                    qnorm = jnp.sqrt(qsq)
            return (lv, gnorm, qnorm, tuple(out_p), tuple(new_s), out_m,
                    new_qr, amp_out, moe_tel)

        def make(batch_treedef, b_specs, mspecs):
            def flat_step(pvals, svals, mvals, qvals, batch_leaves, lr,
                          stepc, seed, amp_in):
                batch = jax.tree_util.tree_unflatten(batch_treedef,
                                                     batch_leaves)
                return _step(pvals, svals, mvals, qvals, batch, lr,
                             stepc, seed, amp_in)

            amp_ispec = (P(),) * 4 if use_scaler else ()
            amp_ospec = (P(),) * 5 if use_scaler else ()
            in_specs = (pspecs, sspecs, mspecs, qspecs, tuple(b_specs),
                        P(), P(), P(), amp_ispec)
            # the trailing P() is a pytree-prefix spec for the MoE
            # telemetry dict: every entry is replicated (psum'd over the
            # batch axes inside the step)
            out_specs = (P(), P(), P(), pspecs, sspecs, mspecs, qspecs,
                         amp_ospec, P())
            sharded = _shard_map(flat_step, mesh, in_specs, out_specs)
            return jax.jit(sharded,
                           donate_argnums=(0, 1, 2, 3) if donate else ())

        def step(batch):
            t_entry = time.perf_counter()
            # fault-injection site for crash/hang tests: fires before
            # any state mutates, so a killed dispatch never tears a step
            _fp.hit("engine.step_dispatch")
            # previous step's loss/grad-norm scalars are fetched HERE
            # (one-step lag): the device has certainly finished the
            # prior step by the next dispatch, so telemetry never adds
            # a sync on the critical path
            self._flush_pending_scalars()
            self._check_mesh_epoch()
            # host-offload prefetch: every offloaded slot re-placed at
            # its live sharding, bucket by bucket, BEFORE the mvals /
            # pvals assembly below reads them. Same shapes, dtypes and
            # shardings every step — the compile key never notices.
            if self._offload is not None:
                self._offload.prefetch_step(self)
            leaves, treedef = jax.tree_util.tree_flatten(
                batch, is_leaf=lambda x: isinstance(x, Tensor))
            leaf_vals = tuple(v._value if isinstance(v, Tensor) else
                              jnp.asarray(v) for v in leaves)
            if batch_specs is not None:
                b_specs = tuple(batch_specs)
            else:
                b_specs = tuple(
                    P(data_axes) if data_axes and v.ndim > 0 else P()
                    for v in leaf_vals)
            n_tok = _batch_tokens(leaf_vals)   # host-local batch tokens
            mvals = {i: opt._master_weights[id(p)]
                     for i, p in zip(t_index, trainable)
                     if id(p) in opt._master_weights}
            mspecs = {i: zero.state_spec(params[i]) for i in mvals}
            # scaler hyperparameters are baked into the trace as Python
            # constants — key them so two differently-configured scalers
            # never share an executable
            amp_key = ((scaler._dynamic, scaler._incr_every,
                        scaler._decr_every, scaler._incr_ratio,
                        scaler._decr_ratio) if use_scaler else None)
            # commledger.ablation_token() keys the exposed-comm
            # profiler's comm-ablated replays OUT of the real program
            # cache (None in normal operation, so live keys are
            # unchanged and steady state stays recompile-free)
            key = (treedef, tuple((v.shape, str(v.dtype))
                                  for v in leaf_vals), b_specs,
                   tuple(sorted(mvals)), amp_key, _cl.ablation_token())
            if not self._profiling:
                self.stats.note("train", key)
            # goodput attribution (observability/goodput): a known key
            # is productive step_compute; a fresh one pays trace + XLA
            # compile in this very call, so the whole dispatch window
            # books as compile. Host-side journal writes only — the
            # compiled program and its cache key are untouched.
            fresh_key = key not in self._compiled
            self._last_dispatch_fresh = (fresh_key
                                         or self._post_restore_warmup)
            _gp_led = None if self._profiling else _gp.current()
            if _gp_led is not None:
                _gp_led.begin("compile" if self._last_dispatch_fresh
                              else "step_compute",
                              step=int(opt._step_count) + 1)
            try:
                return _dispatch(key, treedef, b_specs, mspecs,
                                 leaf_vals, t_entry, n_tok, mvals)
            finally:
                # restore warmup ends at the first dispatch whose key
                # was already compiled: in a relaunched process that is
                # dispatch #2 (dispatch #1 traces; its outputs then
                # shift the avals off the restored arrays' layouts), in
                # an in-process restore it is dispatch #1
                if not fresh_key:
                    self._post_restore_warmup = False
                if _gp_led is not None:
                    _gp_led.end()

        def _dispatch(key, treedef, b_specs, mspecs, leaf_vals,
                      t_entry, n_tok, mvals):
            if key not in self._compiled:
                self._compiled[key] = make(treedef, b_specs, mspecs)
            pvals = tuple(p._value for p in params)
            svals = tuple(opt._states[id(p)] for p in trainable)
            qvals = dict(self._quant_residuals)
            opt._step_count += 1
            self._seed += 1
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            stepc = jnp.asarray(opt._step_count, jnp.int32)
            seed = jnp.asarray(self._seed, jnp.uint32)
            # -1: _step_count was already incremented for THIS step; the
            # traced counter advances inside the step on application
            amp_in = (scaler._traced_state(fallback_step=opt._step_count - 1)
                      if use_scaler else ())
            leaf_vals = _globalize_batch(leaf_vals, b_specs, mesh)
            if _multiprocess(mesh):
                lr = global_put(lr, mesh, P())
                stepc = global_put(stepc, mesh, P())
                seed = global_put(seed, mesh, P())
                # amp state from a previous compiled step is already a
                # committed global array — re-global_put would force a
                # blocking host sync on every step
                if use_scaler and not scaler._dev_global:
                    amp_in = tuple(global_put(v, mesh, P())
                                   for v in amp_in)
                    scaler._dev = amp_in
                    scaler._dev_global = True
            # the capture collects comm notes only if THIS call traces
            # (first execution of the program); cached executions note
            # nothing and reuse the stored ledger
            with _cl.capture() as cap:
                (lv, gnorm, qnorm, new_p, new_s, new_m, new_qr, amp_out,
                 moe_tel) = \
                    self._compiled[key](pvals, svals, mvals, qvals,
                                        leaf_vals, lr, stepc, seed,
                                        amp_in)
            if len(cap):
                self._ledgers[key] = cap
            for k, v in new_qr.items():
                self._quant_residuals[k] = v
            if not self._profiling:
                self._last_key = key
                # example args for on-demand AOT memory analysis of
                # this program (references only; the batch leaves are
                # never donated). Params/states are rebuilt from the
                # engine's CURRENT values at analysis time, so the
                # stored tuple only pins shapes/dtypes/tree structure.
                self._mem_args[key] = (leaf_vals, lr, stepc, seed,
                                       amp_in)
            for p, nv in zip(params, new_p):
                p._value = nv
            for p, ns in zip(trainable, new_s):
                opt._states[id(p)] = ns
            for i, nv in new_m.items():
                opt._master_weights[id(params[i])] = nv
            if use_scaler:
                scaler._store_traced(amp_out)
            # host-offload page-out: the step's FRESH output state (the
            # donated inputs are already dead buffers) moves to the
            # host tier, then the leading buckets start warming on the
            # background thread for the next dispatch
            if self._offload is not None:
                self._offload.page_out_step(self)
            from ..optimizer.lr import LRScheduler

            if isinstance(opt._lr, LRScheduler):
                opt._lr.step()  # advance the schedule once per train step
            if not self._profiling:
                led = self._ledgers.get(key)
                if led is not None:
                    led.publish(self._metrics["comm_bytes"],
                                self._metrics["comm_ops"])
                    # realized per-axis wire compression of this
                    # program (quant_comm payload_ratio stamps); empty
                    # when nothing on the wire is quantized
                    for ax, rv in led.quant_ratios().items():
                        self._metrics["comm_quant_ratio"].set(
                            rv, axis=ax)
                self._note_step(t_entry, n_tok, lv, gnorm,
                                found=amp_out[4] if amp_out else None,
                                qnorm=qnorm if new_qr else None)
                self._pending_moe = moe_tel
            return Tensor(lv, stop_gradient=True)

        return step

    # -- telemetry (observability/) -------------------------------------
    def _flush_pending_scalars(self):
        """Fetch the PREVIOUS step's loss/grad-norm device scalars into
        the loss/grad_norm gauges. Called at the next step's entry (and
        from metrics_snapshot), so the fetch blocks only on work that
        is already done — telemetry adds no sync to the hot path."""
        pend = self._pending_scalars
        moe_pend = self._pending_moe
        self._pending_moe = None
        if moe_pend:
            try:
                _moestats.publish(moe_pend, self._metrics)
            except Exception:
                pass    # a dead device must not take telemetry down
        if pend is None:
            return
        self._pending_scalars = None
        found = self._pending_found
        self._pending_found = None
        qn = self._pending_qnorm
        self._pending_qnorm = None
        lv, gnorm = pend
        try:
            m = self._metrics
            lvf = float(np.asarray(lv))
            gnf = float(np.asarray(gnorm))
            m["loss"].set(lvf)
            m["grad_norm"].set(gnf)
            if qn is not None:
                m["quant_residual_norm"].set(float(np.asarray(qn)))
            # health monitor: robust spike/nonfinite detection on the
            # SAME fetched scalars (one-step lag — still off the hot
            # path; events ring + health_* gauges + goodput journal).
            # A step the AMP GradScaler SKIPPED (found_inf: grads
            # zeroed, update dropped) is protocol, not an anomaly —
            # its scalars never enter the detector's windows.
            if found is None or float(np.asarray(found)) == 0.0:
                self._health.observe(
                    loss=lvf, grad_norm=gnf,
                    step=int(self.optimizer._step_count)
                    if self.optimizer is not None else None)
        except Exception:
            pass        # a dead device must not take telemetry down

    def _note_step(self, t_entry: float, n_tok: int, lv, gnorm,
                   found=None, qnorm=None):
        """Host-side per-step instrumentation on fetched/host values
        only (never called under tracing). ``found``: the traced AMP
        found_inf flag of THIS step (device scalar; fetched with the
        same one-step lag as the loss). ``qnorm``: the quantization
        error-feedback residual norm device scalar (same lag)."""
        now = time.perf_counter()
        m = self._metrics
        m["step_seconds"].observe(now - t_entry)
        m["steps"].inc()
        m["tokens"].inc(n_tok)
        # step-time stall watch on the DISPATCH window (entry to
        # return): unlike the inter-step interval it contains no
        # checkpoint stalls / input waits, and compile dispatches are
        # excluded — so the rolling baseline only ever sees the
        # compiled step itself (coarse thresholds regardless: host
        # noise is real; healthmon docstring)
        if not self._last_dispatch_fresh:
            try:
                self._health.observe(step_seconds=now - t_entry)
            except Exception:
                pass
        # steady-state throughput between step ENTRIES: on an async
        # backend the dispatch returns early, so the inter-step gap is
        # the honest per-step wall time once the pipeline fills
        if self._prev_step_entry is not None:
            dt = max(t_entry - self._prev_step_entry, 1e-9)
            self._last_step_seconds = dt
            tps = n_tok / dt
            m["tokens_per_sec"].set(tps)
            n_params = self._n_params_cfg or sum(
                int(np.prod(p._value.shape)) for p in self.params)
            dev = next(iter(self.mesh.devices.flat))
            peak, _ = _flops.peak_flops_per_chip(dev)
            m["mfu"].set(_flops.mfu(
                n_params, tps * jax.process_count(), self.mesh.size,
                peak, config=getattr(self.model, "config", None)))
        self._prev_step_entry = t_entry
        self._pending_scalars = (lv, gnorm)
        self._pending_found = found
        self._pending_qnorm = qnorm
        # gradient-sync bucketing: how many per-bucket collectives the
        # compiled step issues (0 = the unbucketed tail sync, i.e.
        # sharding_configs["comm_overlap"] off or nothing bucketable)
        m["grad_buckets"].set(
            float(self._bucket_plan.num_buckets)
            if self._bucket_plan is not None else 0.0)
        # pipelined models: publish the analytic bubble fraction of the
        # attached schedule — (S-1)/(vpp*M+S-1) with the circular
        # interleave's vpp as a label, so dashboards can see the
        # schedule regime a run trains under (pp_layers._pipe_fn)
        if getattr(self.model, "_pp_ownership", False) and \
                "pp" in self.mesh.axis_names and self.mesh.shape["pp"] > 1:
            S = getattr(self.model, "_num_stages", 1)
            vpp = getattr(self.model, "_vpp", 1)
            n_mb = getattr(self.model, "_num_microbatches", 1)
            if S > 1:
                m["pp_bubble"].set(
                    (S - 1) / (vpp * n_mb + S - 1), pp_vpp=str(vpp))
        # compile-cache counters: report the delta since last step so
        # the Prometheus counters stay monotonic
        rc, rh = self._stats_reported
        if self.stats.compiles > rc:
            m["compiles"].inc(self.stats.compiles - rc,
                              site="train_engine")
        if self.stats.cache_hits > rh:
            m["cache_hits"].inc(self.stats.cache_hits - rh,
                                site="train_engine")
        self._stats_reported = (self.stats.compiles,
                                self.stats.cache_hits)
        try:
            for d in jax.local_devices():
                ms = d.memory_stats()
                for k in ("bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit"):
                    if ms and k in ms:
                        m["device_memory"].set(
                            ms[k], device=str(d.id), stat=k)
        except Exception:
            pass        # CPU backends may not expose memory_stats
        self._last_tokens = n_tok
        # HBM memory ledger (observability/memledger): knob-gated eager
        # analysis once per program, gauges republished per step, state
        # accounting cached, live-bytes watermark at the step boundary
        if self._mem_on:
            led = self._mem_ledgers.get(self._last_key)
            if led is None:
                led = self.memory_ledger()
            if led is not None:
                led.publish(m, program="train")
            if self._state_acct is None:
                try:
                    self._state_acct = _ml.account_engine(
                        self, batch_tokens=n_tok,
                        accumulate_steps=int(getattr(
                            self.model, "_num_microbatches", 1) or 1))
                except Exception:
                    pass    # accounting must never take the step down
            if self._state_acct is not None:
                self._state_acct.publish(m)
            lb = _ml.live_bytes()
            if lb:
                self._live_peak = max(self._live_peak, lb)
                m["mem_live"].set(lb)
                m["mem_live_peak"].set(self._live_peak)
        # goodput gauges: the live view of the attached run ledger
        # (the crash-durable journal remains the source of truth)
        led_gp = _gp.current()
        if led_gp is not None:
            try:
                led_gp.publish(m)
            except Exception:
                pass    # a dead journal must not take the step down
        from ..observability import get_registry

        get_registry().snapshot()    # feeds the stall flight-record ring

    def metrics_snapshot(self):
        """Fetch pending scalars, then return the registry snapshot —
        the in-process API bench.py emits from."""
        self._flush_pending_scalars()
        from ..observability import get_registry

        return get_registry().snapshot()

    def pod_throughput(self) -> Dict[str, float]:
        """Pod-level tokens/s: every host contributes its local gauge
        through a cross-host all_gather, so rank 0 can report aggregate
        throughput. Call BETWEEN steps (it synchronizes all hosts)."""
        from ..observability import cross_host_sum

        local = self._metrics["tokens_per_sec"].value()
        total = cross_host_sum(local)
        self._metrics["pod_tokens_per_sec"].set(total)
        return {"local_tokens_per_sec": local,
                "pod_tokens_per_sec": total,
                "processes": float(jax.process_count())}

    def pod_step_skew(self) -> Dict[str, Any]:
        """Cross-host straggler check: all-gather every host's last
        inter-step interval (the pod_throughput pattern — synchronizes
        all hosts, call BETWEEN steps) and publish the
        paddle_tpu_health_step_time_skew / slowest_host gauges. A
        persistently hot skew names the straggler host."""
        return self._health.observe_pod_skew(self._last_step_seconds)

    # -- communication accounting (observability/commledger) ------------
    def comm_ledger(self):
        """The static comm ledger of the last-run compiled step (None
        before any step has traced)."""
        return self._ledgers.get(self._last_key)

    # -- memory accounting (observability/memledger) ---------------------
    def memory_ledger(self, key=None):
        """Static HBM memory ledger of the last-run (or given-key)
        compiled train step: lowers the SAME jitted program AOT against
        the engine's current param/state values and reads XLA's
        ``memory_analysis()`` (temp / argument / output / alias / code
        bytes per device). Cached per program key — one extra trace +
        XLA compile the first time, zero thereafter, and the live
        step's jit cache / CompileStats are never touched. Returns
        None before any step has run."""
        key = key if key is not None else self._last_key
        if key is None or key not in self._compiled:
            return None
        led = self._mem_ledgers.get(key)
        if led is not None:
            return led
        stored = self._mem_args.get(key)
        if stored is None or self.optimizer is None:
            return None
        leaf_vals, lr, stepc, seed, amp_in = stored
        opt = self.optimizer
        import contextlib

        with contextlib.ExitStack() as stack:
            # AOT analysis needs live jax.Arrays: page the host tier in
            # for the analysis window, back out after
            if self._offload is not None:
                stack.enter_context(self._offload.resident(self))
            pvals = tuple(p._value for p in self.params)
            svals = tuple(opt._states[id(p)] for p in self.trainable)
            qvals = dict(self._quant_residuals)
            # key[3] pins which params carried masters at trace time
            mvals = {i: opt._master_weights[id(self.params[i])]
                     for i in key[3]}
            led = _ml.analyze(
                self._compiled[key],
                (pvals, svals, mvals, qvals, leaf_vals, lr, stepc, seed,
                 amp_in),
                program="train")
        self._mem_ledgers[key] = led
        return led

    def state_accounting(self, batch_tokens: Optional[int] = None):
        """Measured per-device model-state accounting
        (memledger.account_engine): params / grads / optimizer state /
        master weights at addressable-shard size plus the analytic
        activation-checkpoint term, with the auto_tuner cost-model
        drift. Cached after the first step; ``batch_tokens`` overrides
        the last step's token count for the checkpoint term."""
        if self._state_acct is not None and batch_tokens is None:
            return self._state_acct
        acct = _ml.account_engine(
            self, batch_tokens=int(batch_tokens if batch_tokens
                                   is not None else self._last_tokens),
            accumulate_steps=int(getattr(self.model,
                                         "_num_microbatches", 1) or 1))
        if batch_tokens is None:
            self._state_acct = acct
        return acct

    def roofline_report(self, exposed=None):
        """Roofline bottleneck verdict of the last-run compiled step
        (memledger.roofline): joins the flop accountant (peak
        FLOPs/HBM/ICI tables), the memory ledger's HBM-traffic
        estimate, and the comm ledger — ``exposed`` (an
        ExposedCommReport from profile_exposed_comm) supplies measured
        exposed-ICI seconds and the measured step time; without it the
        analytic wire floor and the last inter-step interval stand in.
        All quantities are one chip's share."""
        n_params = self._n_params_cfg or sum(
            int(np.prod(p._value.shape)) for p in self.params)
        tokens = self._last_tokens * jax.process_count()
        fl = _flops.train_flops_per_token(
            n_params, config=getattr(self.model, "config", None)) \
            * tokens / max(self.mesh.size, 1)
        led = self.memory_ledger()
        traffic = led.traffic_bytes if led is not None and \
            led.available else 0.0
        comm = self.comm_ledger()
        wire = comm.bytes_for() if comm is not None else 0.0
        exp_ici = None
        step_s = self._last_step_seconds
        if exposed is not None:
            exp_ici = sum(exposed.exposed_seconds.values())
            step_s = exposed.step_seconds or step_s
        dev = next(iter(self.mesh.devices.flat))
        return _ml.roofline(
            step_seconds=step_s, flops_per_step=fl,
            hbm_traffic_bytes=traffic, wire_bytes=wire, device=dev,
            exposed_ici_seconds=exp_ici, program="train")

    def _state_snapshot(self):
        """Device-copy of everything a step mutates (jnp.copy keeps
        each array's sharding; immutable host-tier entries pass
        through by reference), so offline replays can be undone."""
        from . import host_offload as _ho

        def _copy(v):
            if _ho.is_host(v) or not hasattr(v, "shape"):
                return v
            return jnp.copy(v)

        opt = self.optimizer
        snap = {
            "params": [_copy(p._value) for p in self.params],
            "states": {id(p): {k: _copy(v)
                               for k, v in opt._states[id(p)].items()}
                       for p in self.trainable if id(p) in opt._states},
            "masters": {k: _copy(v)
                        for k, v in opt._master_weights.items()},
            "qresid": {k: _copy(v)
                       for k, v in self._quant_residuals.items()},
            "step_count": opt._step_count,
            "seed": self._seed,
            "pending": self._pending_scalars,
            "pending_found": self._pending_found,
            "pending_qnorm": self._pending_qnorm,
            "pending_moe": self._pending_moe,
        }
        from ..optimizer.lr import LRScheduler

        if isinstance(opt._lr, LRScheduler):
            snap["lr_state"] = dict(opt._lr.__dict__)
        return snap

    def _state_restore(self, snap):
        opt = self.optimizer
        for p, v in zip(self.params, snap["params"]):
            p._value = v
        for pid, st in snap["states"].items():
            opt._states[pid] = st
        opt._master_weights = dict(snap["masters"])
        self._quant_residuals = dict(snap["qresid"])
        opt._step_count = snap["step_count"]
        self._seed = snap["seed"]
        self._pending_scalars = snap["pending"]
        self._pending_found = snap["pending_found"]
        self._pending_qnorm = snap["pending_qnorm"]
        self._pending_moe = snap["pending_moe"]
        if "lr_state" in snap:
            opt._lr.__dict__.update(snap["lr_state"])

    # -- crash-consistent checkpointing (distributed/checkpoint) ---------
    def _checkpoint_state(self, scaler=None):
        """The full training state as (sharded state dict, scalar meta):
        params (+ buffers), optimizer moments in their live ZeRO/tp/pp
        sharding (shard-exact for pp x vpp stacked chunks — the state
        dict holds the global jax.Arrays, whose addressable shards the
        writer records with global offsets), AMP master weights, the
        GradScaler protocol state, step counters, the LR schedule, and
        the per-process RNG streams. Everything a bit-exact resume
        needs rides in ONE commit unit."""
        from ..core import rng as _rng_mod
        from ..optimizer.lr import LRScheduler
        from .fleet.elastic.resume import opt_state_tensors

        state: Dict[str, Any] = {"model": self.model.state_dict()}
        opt = self.optimizer
        meta: Dict[str, Any] = {"format": 1,
                                "engine_seed": int(self._seed)}
        if opt is not None:
            self._ensure_opt_states()
            meta["opt_step_count"] = int(opt._step_count)
            # optimizer state keyed by STRUCTURED model names (auto
            # p.name counters shift across in-process rebuilds)
            _, tensors = opt_state_tensors(self.model, opt)
            if tensors:
                state["optim"] = tensors
            if isinstance(opt._lr, LRScheduler):
                meta["lr_scheduler"] = opt._lr.state_dict()
            else:
                meta["lr"] = float(opt.get_lr())
        if scaler is not None:
            meta["scaler"] = scaler.state_dict()
        # quantized-collective error-feedback residuals (quant_comm):
        # per-bucket rank-local compression error carried as training
        # state — a resume that silently dropped it would re-inject the
        # lost gradient mass as a one-step bias, so it commits in the
        # SAME unit as params/moments (shard-exact: dim 0 is sharded
        # over every mesh axis, each process writes its own windows)
        if self._quant_residuals:
            state["quant_residual"] = dict(self._quant_residuals)
            meta["quant_residual_keys"] = sorted(self._quant_residuals)
        # per-process RNG streams: the host key + every named tracker
        # stream, keyed by process index so each relaunched rank gets
        # ITS stream back (the in-step per-rank forking derives from
        # engine_seed + axis_index, so it resumes exactly by itself)
        pi = jax.process_index()
        rng: Dict[str, Any] = {
            f"key_proc{pi}": np.asarray(
                jax.random.key_data(_rng_mod.get_rng_state()))}
        for name, key in _rng_mod.get_rng_tracker().states_.items():
            rng[f"tracker.{name}.proc{pi}"] = np.asarray(
                jax.random.key_data(key))
        state["rng"] = rng
        return state, meta

    def save_checkpoint(self, path: Optional[str] = None, *,
                        manager=None, step: Optional[int] = None,
                        scaler=None, extra_meta: Optional[Dict] = None,
                        async_save: bool = False) -> None:
        """Write a crash-consistent checkpoint of the engine's whole
        training state (see ``_checkpoint_state``).

        ``path``: one atomic checkpoint directory; or pass ``manager``
        (a ``checkpoint.CheckpointManager``) for rolling keep-last-k
        retention. ``async_save``/the manager's async mode stall only
        for the device→host snapshot; the commit happens in the
        background (``checkpoint.wait_async_saves()`` /
        ``manager.wait()`` to join). ``step`` defaults to the
        optimizer's applied-step count."""
        import contextlib

        from ..core.enforce import enforce

        with contextlib.ExitStack() as stack:
            # host-offloaded state pages in for the save window: the
            # checkpoint format (and its resharding metadata) is
            # IDENTICAL with the knob on or off, so restores cross the
            # offload boundary freely. The device->host snapshot
            # happens inside manager.save()/save_state_dict before the
            # exit pages everything back out.
            if self._offload is not None:
                stack.enter_context(self._offload.resident(self))
            state, meta = self._checkpoint_state(scaler)
            if step is None:
                step = meta.get("opt_step_count", 0)
            meta["step"] = int(step)
            if extra_meta:
                meta.update(extra_meta)
            if manager is not None:
                manager.save(state, step=int(step), extra_meta=meta)
            else:
                enforce(path is not None,
                        "save_checkpoint needs a path or a "
                        "CheckpointManager")
                from .checkpoint import save_state_dict

                save_state_dict(state, path, async_save=async_save,
                                extra_meta=meta)

    def restore_checkpoint(self, path: str, scaler=None) -> Dict[str, Any]:
        """Restore the engine (in place) from a committed checkpoint:
        params, optimizer moments + master weights (resharded to the
        CURRENT topology via the metadata's global offsets), scaler,
        counters, LR schedule, RNG streams. Returns the checkpoint's
        meta dict (incl. ``step``).

        Restoring never changes a shape, dtype, sharding spec, or the
        master-weight key set, so already-compiled steps keep hitting
        their cache — 0 recompiles after restore (pinned by tests).
        Restore also never touches CompileStats: the warmup compile of
        a restored engine books as a compile exactly once, and a
        restore into an already-compiled engine books nothing (pinned
        by tests against the registry counters too). Wall time spent
        here is journaled as the goodput ``restore`` segment."""
        import contextlib

        with _gp.segment("restore"):
            with contextlib.ExitStack() as stack:
                # the load targets are built from the live state dicts,
                # so the host tier pages in first; the exit pages the
                # LOADED arrays back out — the host-tier buffers are
                # rebuilt from the checkpoint bytes deterministically
                # (pinned by the SIGKILL-mid-prefetch crash matrix)
                if self._offload is not None:
                    stack.enter_context(self._offload.resident(self))
                meta = self._restore_checkpoint_inner(path, scaler)
        self._post_restore_warmup = True
        return meta

    def _restore_checkpoint_inner(self, path: str, scaler=None
                                  ) -> Dict[str, Any]:
        from ..core import rng as _rng_mod
        from ..optimizer.lr import LRScheduler
        from .checkpoint import load_state_dict, read_extra_meta, \
            resolve_committed

        resolved = resolve_committed(path)
        from ..core.enforce import enforce

        enforce(resolved is not None,
                f"no committed checkpoint at {path!r} "
                "(checkpoint.latest_committed(base) finds the newest "
                "committed one under a CheckpointManager base dir)")
        meta = read_extra_meta(resolved)
        from .fleet.elastic.resume import (_apply_opt_state,
                                           opt_state_tensors)

        opt = self.optimizer
        # phase 1: model params FIRST — optimizer state materialized
        # below (fresh AMP masters) must copy the LOADED weights
        targets: Dict[str, Any] = {"model": self.model.state_dict()}
        load_state_dict(targets, resolved)
        if opt is not None:
            self._ensure_opt_states()
            slots, tensors = opt_state_tensors(self.model, opt)
            if tensors:
                load_state_dict({"optim": tensors}, resolved)
                _apply_opt_state(opt, slots, tensors)
            opt._step_count = int(meta.get("opt_step_count",
                                           meta.get("step", 0)))
            if "lr_scheduler" in meta and isinstance(opt._lr,
                                                     LRScheduler):
                opt._lr.set_state_dict(meta["lr_scheduler"])
            if "lr" in meta and not isinstance(opt._lr, LRScheduler):
                opt.set_lr(float(meta["lr"]))
        self._seed = int(meta.get("engine_seed", self._seed))
        if scaler is not None and "scaler" in meta:
            scaler.load_state_dict(meta["scaler"])
        # quantization error-feedback residuals: materialize the (zero)
        # buffers from the deterministic bucket plan, then overwrite
        # with the checkpointed bytes at the live sharding. Checkpoints
        # written without quant_comm (or restored into an engine with
        # the knob off) skip this — the buffers stay zeros / absent.
        qkeys = meta.get("quant_residual_keys") or []
        if qkeys:
            self._ensure_quant_state()
            targets = {k: self._quant_residuals[k] for k in qkeys
                       if k in self._quant_residuals}
            if targets:
                loaded = {"quant_residual": dict(targets)}
                load_state_dict(loaded, resolved)
                for k, arr in loaded["quant_residual"].items():
                    # the loader hands raw (non-Tensor) leaves back as
                    # host arrays — re-place at the live sharding
                    if not isinstance(arr, jax.Array):
                        self._quant_residuals[k] = global_put(
                            np.asarray(arr, dtype=np.float32),
                            self.mesh, self._quant_specs[k])
        # per-process RNG streams (missing entries — e.g. resuming on
        # MORE hosts than saved — keep their current stream)
        pi = jax.process_index()
        rng_t: Dict[str, Any] = {f"key_proc{pi}": np.zeros(0)}
        tracker = _rng_mod.get_rng_tracker()
        for name in tracker.states_:
            rng_t[f"tracker.{name}.proc{pi}"] = np.zeros(0)
        rng_targets = {"rng": rng_t}
        load_state_dict(rng_targets, resolved)
        key = rng_targets["rng"][f"key_proc{pi}"]
        if getattr(key, "size", 0):
            _rng_mod.set_rng_state(
                jax.random.wrap_key_data(jnp.asarray(key)))
        for name in tracker.states_:
            kd = rng_targets["rng"][f"tracker.{name}.proc{pi}"]
            if getattr(kd, "size", 0):
                tracker.states_[name] = jax.random.wrap_key_data(
                    jnp.asarray(kd))
        return meta

    @staticmethod
    def _time_calls(fn, repeats: int) -> float:
        """Median wall time of ``fn()`` over ``repeats`` blocked calls
        (one unmeasured warmup call first — it may compile)."""
        jax.block_until_ready(fn())
        times = []
        for _ in range(max(repeats, 1)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    def profile_exposed_comm(self, step, batch, repeats: int = 3,
                             publish: bool = True):
        """Exposed-comm attribution: split each mesh axis's comm time
        into exposed vs overlapped (observability/commledger.py).

        For every axis label in the step's comm ledger this compiles a
        REPLAY of the same step with that axis's collectives ablated to
        shape-preserving local ops, and a standalone back-to-back
        replay of the axis's recorded collectives; then

        - exposed(axis) = t(full) - t(ablated): what the axis's comm
          adds to the critical path,
        - replay(axis): the axis's total comm time, nothing hiding it,
        - comm_exposed_fraction{axis} = exposed / max(replay, exposed),
        - grad_sync_exposed_seconds = exposed summed over dp/sharding.

        Offline only: params / optimizer state / rng / lr schedule are
        snapshotted and restored (the ablated replays compute garbage
        on purpose), telemetry counters and CompileStats are suppressed
        while it runs, and the replay executables are dropped from the
        program cache afterwards — the next real step hits the original
        compiled program. Run between steps, never under an AMP
        GradScaler whose state you care about.

        Returns an ``ExposedCommReport``; ``publish=True`` also sets
        the comm_exposed_* / grad_sync_exposed_seconds gauges.
        """
        self._flush_pending_scalars()
        led = self.comm_ledger()
        if led is None or not len(led):
            rep = _cl.build_report(0.0, {}, {})
            if publish:
                rep.publish(self._metrics)
            return rep
        snap = self._state_snapshot()
        self._profiling = True
        try:
            t_full = self._time_calls(lambda: step(batch)._value, repeats)
            exposed: Dict[str, float] = {}
            replay: Dict[str, float] = {}
            for label in led.axis_labels():
                with _cl.ablate({label}):
                    t_abl = self._time_calls(
                        lambda: step(batch)._value, repeats)
                exposed[label] = t_full - t_abl
                recs = [r for r in led.records if r.axis == label]
                rfn = _cl.replay_callable(recs, self.mesh, _shard_map,
                                          jax.jit)
                replay[label] = self._time_calls(rfn, repeats)
        finally:
            self._profiling = False
            self._state_restore(snap)
            # drop the ablated executables (ablation_token is the last
            # key component; None marks the real programs)
            self._compiled = {k: v for k, v in self._compiled.items()
                              if k[-1] is None}
            self._ledgers = {k: v for k, v in self._ledgers.items()
                             if k[-1] is None}
            self._mem_ledgers = {k: v for k, v
                                 in self._mem_ledgers.items()
                                 if k[-1] is None}
            self._mem_args = {k: v for k, v in self._mem_args.items()
                              if k[-1] is None}
        rep = _cl.build_report(t_full, exposed, replay)
        if publish:
            rep.publish(self._metrics)
        return rep

    def _check_mesh_epoch(self):
        if C.mesh_epoch() != self._mesh_epoch:
            from ..core.enforce import PreconditionNotMetError

            raise PreconditionNotMetError(
                "the world mesh was rebuilt (split_group factored an "
                "axis) after this ParallelEngine was created; its "
                "compiled steps reference deleted axis names. Call "
                "split_group BEFORE building engines/shardings, or "
                "recreate the ParallelEngine.")

    # -- forward-only (eval / inference) --------------------------------
    def eval_step(self, fn: Callable, batch_specs=None):
        mesh = self.mesh
        data_axes = _mesh_data_axes(mesh)
        params = self.params
        zero = self._zero
        pspecs = tuple(zero.storage_spec(p) for p in params)
        compiled: Dict[Any, Callable] = {}

        def make(treedef, b_specs, out_spec):
            def flat_fwd(pvals, batch_leaves):
                pvals = list(pvals)
                for i, p in enumerate(params):
                    e = zero.entry(p)
                    if e is not None and e[1]:
                        pvals[i] = C.t_all_gather(pvals[i], zero.axis,
                                                  axis=e[0], tiled=True)
                pvals = tuple(pvals)
                with C.spmd_region(), bind_params(params, pvals), \
                        _ad.no_grad():
                    batch = jax.tree_util.tree_unflatten(treedef,
                                                         batch_leaves)
                    t_batch = jax.tree_util.tree_map(
                        lambda v: Tensor(v, stop_gradient=True), batch)
                    out = fn(self.model, t_batch)
                    return (out._value if isinstance(out, Tensor) else
                            jax.tree_util.tree_map(
                                lambda t: t._value if isinstance(t, Tensor)
                                else t, out))

            sharded = _shard_map(flat_fwd, mesh,
                                 (pspecs, tuple(b_specs)), out_spec)
            return jax.jit(sharded)

        def step(batch, out_spec=None):
            self._check_mesh_epoch()
            # host-offloaded param shards must be live before the
            # p._value assembly below (they page out again at the next
            # train step)
            if self._offload is not None:
                self._offload.restore_params(self)
            leaves, treedef = jax.tree_util.tree_flatten(
                batch, is_leaf=lambda x: isinstance(x, Tensor))
            leaf_vals = tuple(v._value if isinstance(v, Tensor) else
                              jnp.asarray(v) for v in leaves)
            b_specs = (tuple(batch_specs) if batch_specs is not None else
                       tuple(P(data_axes) if data_axes and v.ndim > 0
                             else P() for v in leaf_vals))
            ospec = out_spec if out_spec is not None else (
                P(data_axes) if data_axes else P())
            key = (treedef, tuple((v.shape, str(v.dtype))
                                  for v in leaf_vals), b_specs, str(ospec))
            self.stats.note("eval", key)
            if key not in compiled:
                compiled[key] = make(treedef, b_specs, ospec)
            leaf_vals = _globalize_batch(leaf_vals, b_specs, mesh)
            out = compiled[key](tuple(p._value for p in params), leaf_vals)
            return jax.tree_util.tree_map(
                lambda v: Tensor(v, stop_gradient=True), out)

        return step
