"""Collective communication over the TPU mesh.

TPU-native replacement for the reference's ProcessGroup stack
(reference: paddle/fluid/distributed/collective/process_group.h:47 virtual
AllReduce/AllGather/AllToAll/...; process_group_nccl.cc NCCL rings;
phi/core/distributed/nccl_comm_context.h:40 per-ring comm contexts;
python surface python/paddle/distributed/communication/).

Design: a ``Group`` is backed by one or more *mesh axis names* of a
``jax.sharding.Mesh`` instead of an NCCL communicator. Inside an SPMD
region (the training step traced under ``jax.shard_map`` — entered via
``spmd_region``/the Fleet engine), each collective lowers to the XLA
collective HLO (psum/all_gather/ppermute/all_to_all) on those axes,
riding ICI. Outside an SPMD region with world_size==1 the collectives
are identities, matching the reference's single-card behavior.

The "channel id"/ring-id bookkeeping of NCCL disappears: XLA assigns
channel ids at compile time from the axis structure.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..autograd import engine as _engine
from ..core.dispatch import def_op
from ..core.enforce import PreconditionNotMetError, enforce
from ..tensor import Tensor

__all__ = [
    "ReduceOp", "Group", "ProcessGroup", "init_parallel_env", "new_group",
    "get_group", "get_rank", "get_world_size", "all_reduce", "all_gather",
    "all_gather_object", "broadcast_object_list", "all_to_all",
    "reduce_scatter", "broadcast",
    "reduce", "scatter", "send", "recv", "isend", "irecv", "barrier",
    "spmd_region", "in_spmd_region", "split_group", "stream",
    "all_reduce_mean_value", "wait", "ppermute", "axis_index",
    "gather_object",
]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group = a set of mesh axis names.

    ``nranks`` is the product of the axis sizes. ``rank`` is only
    meaningful inside an SPMD region where it is a *traced* value
    (lax.axis_index) — Python-level code must branch with lax.cond/where,
    never `if rank == k:` (XLA semantics; see SURVEY.md §7 hard parts).
    """

    _next_gid = 0

    def __init__(self, axis_names: Tuple[str, ...], nranks: int,
                 name: str = "", pg=None):
        self.axis_names = tuple(axis_names)
        self.nranks = nranks
        self.name = name or "+".join(axis_names) or "world"
        self.id = Group._next_gid
        Group._next_gid += 1
        self.process_group = pg

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self):
        if in_spmd_region() and self.axis_names:
            return axis_index(self.axis_names)
        return 0

    def get_group_rank(self, global_rank):
        return global_rank

    def __repr__(self):
        return f"Group(axes={self.axis_names}, nranks={self.nranks})"


# ProcessGroup alias keeps the reference's C++-facing name alive for users.
ProcessGroup = Group


class _World:
    def __init__(self):
        self.mesh: Optional[jax.sharding.Mesh] = None
        self.groups: Dict[int, Group] = {}
        self.default_group: Optional[Group] = None
        self.initialized = False
        self.rank = 0
        self.world_size = 1
        # Bumped whenever the world mesh is REBUILT (split_group axis
        # factoring). Engines/compiled steps snapshot the epoch at build
        # time and refuse to run against a newer mesh — shardings compiled
        # against deleted axis names must not silently execute.
        self.mesh_epoch = 0


_world = _World()
_spmd = threading.local()


def _mesh_devices(n: Optional[int] = None):
    devs = jax.devices()
    return devs if n is None else devs[:n]


def init_parallel_env(mesh: Optional[jax.sharding.Mesh] = None,
                      strategy=None) -> Group:
    """(reference: python/paddle/distributed/parallel.py:943-1101 —
    TCPStore rendezvous → ProcessGroup creation. TPU-native: the same
    TCPStore bootstraps ``jax.distributed.initialize`` (runtime.py), after
    which ``jax.devices()`` is the GLOBAL device list; the world mesh is
    built over it and in-graph collectives cross processes.)"""
    from . import runtime as _rt

    _rt.ensure_initialized()
    if _world.initialized and mesh is None:
        return _world.default_group
    if mesh is None:
        devs = np.array(_mesh_devices())
        mesh = jax.sharding.Mesh(devs, ("world",))
    _world.mesh = mesh
    _world.world_size = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    _world.rank = _process_rank()
    g = Group(tuple(mesh.axis_names), _world.world_size, name="world")
    _world.default_group = g
    _world.groups[0] = g
    _world.initialized = True
    return g


def _process_rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


def is_initialized() -> bool:
    return _world.initialized


def get_world_mesh() -> Optional[jax.sharding.Mesh]:
    return _world.mesh


def mesh_epoch() -> int:
    """Current world-mesh generation (see _World.mesh_epoch)."""
    return _world.mesh_epoch


def get_rank(group: Optional[Group] = None):
    if in_spmd_region():
        g = group or _world.default_group
        if g is not None and g.axis_names:
            return axis_index(g.axis_names)
    return _world.rank


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    return _world.world_size


def get_group(gid: int = 0) -> Group:
    return _world.groups.get(gid, _world.default_group)


def new_group(ranks=None, backend=None, timeout=None,
              axis_names: Optional[Sequence[str]] = None,
              nranks: Optional[int] = None, name: str = "") -> Group:
    """Create a subgroup. TPU-native: subgroups are mesh axes; ``ranks``
    lists are accepted for API parity (the topology layer translates rank
    lists into axes when building the hybrid mesh)."""
    if axis_names is not None:
        mesh = _world.mesh
        n = nranks or int(np.prod([mesh.shape[a] for a in axis_names])) \
            if mesh is not None else (nranks or 1)
        g = Group(tuple(axis_names), n, name=name)
    else:
        n = len(ranks) if ranks else _world.world_size
        g = Group((), n, name=name or f"ranks{ranks}")
        g._ranks = list(ranks) if ranks else list(range(n))
    _world.groups[g.id] = g
    return g


def split_group(parent: Group, every: int) -> Group:
    """Split ``parent`` into contiguous subgroups of size ``every``.

    TPU-native: a mesh axis of size ``n = k*every`` factors into
    ``(outer k, inner every)``; the subgroup is the *inner* axis. When
    the world mesh owns the parent axis we reshape it into two axes and
    return a Group over the inner one (reference analog:
    python/paddle/distributed/communication/group.py split by rank list).
    """
    enforce(parent.nranks % every == 0,
            f"split_group: {parent.nranks} ranks not divisible by {every}")
    if parent.nranks == every:
        return parent
    mesh = _world.mesh
    if mesh is not None and len(parent.axis_names) == 1 \
            and parent.axis_names[0] in mesh.axis_names:
        ax = parent.axis_names[0]
        outer = parent.nranks // every
        inner_name, outer_name = f"{ax}_in{every}", f"{ax}_out{every}"
        if inner_name not in mesh.axis_names:
            # rebuild the world mesh with the parent axis factored
            # (outer-major, so linearised (outer, inner) order == the
            # original axis order) and rewrite EVERY existing group that
            # referenced the old axis onto the (outer, inner) pair —
            # psum over both sub-axes is exactly psum over the original
            # axis, so their collectives keep the same semantics.
            axes, sizes = [], []
            for a in mesh.axis_names:
                if a == ax:
                    axes += [outer_name, inner_name]
                    sizes += [outer, every]
                else:
                    axes.append(a)
                    sizes.append(mesh.shape[a])
            _world.mesh = jax.sharding.Mesh(
                mesh.devices.reshape(sizes), tuple(axes))
            _world.mesh_epoch += 1  # invalidate engines built on old axes
            for g in _world.groups.values():
                if ax in g.axis_names:
                    g.axis_names = tuple(
                        sub for a in g.axis_names
                        for sub in ((outer_name, inner_name) if a == ax
                                    else (a,)))
        g = Group((inner_name,), every, name=f"{parent.name}/{every}")
        _world.groups[g.id] = g
        return g
    # no owning mesh axis: host-side subgroup — members are the
    # contiguous block of `every` ranks containing THIS process
    from . import runtime as _rt

    lo = (_rt.process_rank() // every) * every
    g = Group((), every, name=f"{parent.name}/{every}")
    g._ranks = list(range(lo, lo + every))
    _world.groups[g.id] = g
    return g


# ---------------------------------------------------------------------------
# SPMD region context
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def spmd_region(mesh: Optional[jax.sharding.Mesh] = None):
    """Marks that the code is being traced inside jax.shard_map, so
    collectives emit XLA collective ops with axis names."""
    prev = getattr(_spmd, "depth", 0)
    _spmd.depth = prev + 1
    try:
        yield
    finally:
        _spmd.depth = prev


def in_spmd_region() -> bool:
    return getattr(_spmd, "depth", 0) > 0


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis inside an SPMD region.

    Compat shim: ``lax.axis_size`` only exists in newer jax; a psum over
    a python int constant-folds to the axis size at trace time on every
    version."""
    return lax.psum(1, axis_name)


def axis_index(axis_names: Tuple[str, ...]):
    """Linearised rank within the (possibly multi-axis) group."""
    idx = lax.axis_index(axis_names[0])
    for a in axis_names[1:]:
        idx = idx * axis_size(a) + lax.axis_index(a)
    return idx


# ---------------------------------------------------------------------------
# Traced-collective shim (the comm ledger's interposition point).
#
# EVERY in-graph collective in the tree funnels through these t_*
# wrappers instead of calling lax.* directly, so that
# observability/commledger.py sees each one at TRACE time (op kind,
# axes, local shape/dtype, group size) and the exposed-comm profiler
# can ablate an axis's collectives into shape-preserving local ops.
# With no capture and no ablation active they ARE the lax call — the
# fast path adds one predicate per traced call site and nothing to the
# compiled program.
# ---------------------------------------------------------------------------


def _flat_axes(axes) -> Tuple[str, ...]:
    if isinstance(axes, str):
        return (axes,)
    flat: List[str] = []
    for a in axes:
        flat.extend(a if isinstance(a, (tuple, list)) else (a,))
    return tuple(flat)


def _group_size(axes: Tuple[str, ...]) -> int:
    p = 1
    for a in axes:
        p *= int(axis_size(a))
    return p


def _note_shim(op: str, axes, x, args: Tuple = ()):
    """If the ledger is active: note the collective and answer
    (group size, is-this-axis-group-ablated). Trace-time host
    bookkeeping only — adds nothing to the compiled program."""
    from ..observability import commledger as cl

    if not cl.active():
        return None, False
    flat = _flat_axes(axes)
    p = _group_size(flat)
    cl.note(op, flat, tuple(getattr(x, "shape", ())),
            getattr(x, "dtype", "float32"), p, args)
    return p, cl.ablating("+".join(flat))


def t_psum(x, axes):
    p, abl = _note_shim("psum", axes, x)
    return x if abl else lax.psum(x, axes)


def t_pmean(x, axes):
    # wire-identical to psum (ledger kind "psum"); ablated = identity
    p, abl = _note_shim("psum", axes, x)
    return x if abl else lax.pmean(x, axes)


def t_pmax(x, axes):
    p, abl = _note_shim("pmax", axes, x)
    return x if abl else lax.pmax(x, axes)


def t_pmin(x, axes):
    p, abl = _note_shim("pmin", axes, x)
    return x if abl else lax.pmin(x, axes)


def _abl_gather(x, p, axis):
    """Ablated all_gather: p local copies (shape-preserving stand-in)."""
    return jnp.concatenate([x] * p, axis=axis)


def _abl_scatter(x, p, dim):
    """Ablated reduce_scatter: keep the leading 1/p local chunk."""
    return lax.slice_in_dim(x, 0, x.shape[dim] // p, axis=dim)


def _abl_a2a(x, p, split_axis, concat_axis):
    """Ablated all_to_all: local reshuffle with the same output shape."""
    if split_axis == concat_axis:
        return x
    y = lax.slice_in_dim(x, 0, x.shape[split_axis] // p, axis=split_axis)
    return jnp.concatenate([y] * p, axis=concat_axis)


def t_all_gather(x, axes, axis=0, tiled=True):
    p, abl = _note_shim("all_gather", axes, x, (int(axis),))
    return _abl_gather(x, p, axis) if (abl and tiled) else \
        lax.all_gather(x, axes, axis=axis, tiled=tiled)


def t_psum_scatter(x, axes, scatter_dimension=0, tiled=True):
    p, abl = _note_shim("reduce_scatter", axes, x,
                        (int(scatter_dimension),))
    return _abl_scatter(x, p, scatter_dimension) if (abl and tiled) else \
        lax.psum_scatter(x, axes, scatter_dimension=scatter_dimension,
                         tiled=tiled)


def t_all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=True):
    p, abl = _note_shim("all_to_all", axes, x,
                        (int(split_axis), int(concat_axis)))
    return _abl_a2a(x, p, split_axis, concat_axis) if (abl and tiled) \
        else lax.all_to_all(x, axes, split_axis=split_axis,
                            concat_axis=concat_axis, tiled=tiled)


def t_ppermute(x, axes, perm):
    perm = tuple(tuple(pr) for pr in perm)
    flat = _flat_axes(axes)
    _, abl = _note_shim("ppermute", flat, x, (perm,))
    return x if abl else lax.ppermute(
        x, flat[0] if len(flat) == 1 else flat, perm=list(perm))


# ---------------------------------------------------------------------------
# Collective kernels (registered ops so autograd records them; analog of
# phi collective kernels phi/kernels/gpu/all_reduce_kernel.cu etc.)
# ---------------------------------------------------------------------------


def _psum_like(x, op: int, axes):
    if op == ReduceOp.SUM:
        return t_psum(x, axes)
    if op == ReduceOp.MAX:
        return t_pmax(x, axes)
    if op == ReduceOp.MIN:
        return t_pmin(x, axes)
    if op == ReduceOp.AVG:
        return t_pmean(x, axes)
    if op == ReduceOp.PROD:
        # sign/zero-correct product: magnitude via exp∘psum∘log of |x|,
        # sign via negative-count parity, zero if any member holds a zero
        zero = t_pmax((x == 0).astype(x.dtype), axes)
        negs = t_psum((x < 0).astype(jnp.int32), axes)
        sign = jnp.where(negs % 2 == 0, jnp.ones_like(x), -jnp.ones_like(x))
        safe = jnp.where(x == 0, jnp.ones_like(x), jnp.abs(x))
        mag = jnp.exp(t_psum(jnp.log(safe), axes))
        return jnp.where(zero > 0, jnp.zeros_like(x), sign * mag)
    raise ValueError(f"bad reduce op {op}")


@def_op("c_allreduce")
def _c_allreduce(x, op=0, axes=()):
    return _psum_like(x, op, axes)


@def_op("c_allgather")
def _c_allgather(x, axes=(), axis=0):
    return t_all_gather(x, axes, axis=axis, tiled=True)


@def_op("c_reducescatter")
def _c_reducescatter(x, axes=(), axis=0):
    return t_psum_scatter(x, axes, scatter_dimension=axis, tiled=True)


@def_op("c_alltoall")
def _c_alltoall(x, axes=(), split_axis=0, concat_axis=0):
    return t_all_to_all(x, axes, split_axis=split_axis,
                        concat_axis=concat_axis, tiled=True)


@def_op("c_broadcast")
def _c_broadcast(x, axes=(), src=0):
    # broadcast = select src's value on every member
    idx = axis_index(axes)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return t_psum(masked, axes)


@def_op("c_ppermute")
def _c_ppermute(x, axes=(), perm=()):
    return t_ppermute(x, axes, perm)


# ---------------------------------------------------------------------------
# Public API (python/paddle/distributed/communication parity)
# ---------------------------------------------------------------------------


def _group_axes(group: Optional[Group]):
    g = group or _world.default_group
    if g is None or not g.axis_names:
        # a rank-list group with >1 members but no mesh axis cannot lower
        # to an XLA collective — silently becoming an identity would be a
        # correctness trap, so fail loudly inside traced SPMD code
        if (in_spmd_region() and g is not None and g.nranks > 1
                and getattr(g, "_ranks", None)):
            raise PreconditionNotMetError(
                f"group {g.name!r} was created from a rank list without a "
                f"mesh axis; inside an SPMD region collectives need mesh "
                f"axes — create the group via the hybrid topology "
                f"(fleet.init) or new_group(axis_names=...)")
        return None
    return g.axis_names


def _noop(tensor):
    return tensor


def all_reduce(tensor: Tensor, op: int = ReduceOp.SUM,
               group: Optional[Group] = None, sync_op: bool = True):
    axes = _group_axes(group)
    if not in_spmd_region() or axes is None:
        return tensor  # world of 1 (or outside SPMD): identity
    out = _c_allreduce(tensor, op=op, axes=axes)
    tensor._value = out._value
    tensor._grad_node = out._grad_node
    tensor._out_idx = out._out_idx
    tensor.stop_gradient = out.stop_gradient
    return tensor


def all_reduce_mean_value(tensor: Tensor, group: Optional[Group] = None):
    axes = _group_axes(group)
    if not in_spmd_region() or axes is None:
        return tensor
    return _c_allreduce(tensor, op=ReduceOp.AVG, axes=axes)


def all_gather(tensor_list: Optional[List], tensor: Tensor = None,
               group: Optional[Group] = None, sync_op: bool = True, axis=0):
    """paddle signature: all_gather(tensor_list, tensor). Returns stacked
    result; also fills tensor_list if given."""
    if tensor is None:
        tensor, tensor_list = tensor_list, None
    axes = _group_axes(group)
    if not in_spmd_region() or axes is None:
        if tensor_list is not None:
            tensor_list.append(tensor)
        return tensor
    out = _c_allgather(tensor, axes=axes, axis=axis)
    if tensor_list is not None:
        n = (group or _world.default_group).nranks
        from ..ops.manipulation import split as _split

        tensor_list.extend(_split(out, n, axis=axis))
    return out


def reduce_scatter(tensor: Tensor, tensor_or_tensor_list=None, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op=True, axis=0):
    axes = _group_axes(group)
    src = tensor_or_tensor_list if tensor_or_tensor_list is not None else tensor
    if isinstance(src, (list, tuple)):
        from ..ops.manipulation import concat as _concat

        src = _concat(list(src), axis=axis)
    if not in_spmd_region() or axes is None:
        return src
    return _c_reducescatter(src, axes=axes, axis=axis)


def all_to_all(out_tensor_list, in_tensor_list=None,
               group: Optional[Group] = None, sync_op: bool = True):
    """List-form paddle API; also accepts a single stacked tensor."""
    single = not isinstance(out_tensor_list, list) or in_tensor_list is None
    if in_tensor_list is None:
        x = out_tensor_list
    else:
        from ..ops.manipulation import concat as _concat

        x = _concat(list(in_tensor_list), axis=0) if isinstance(
            in_tensor_list, (list, tuple)) else in_tensor_list
    axes = _group_axes(group)
    if in_spmd_region() and axes is not None:
        out = _c_alltoall(x, axes=axes, split_axis=0, concat_axis=0)
    else:
        out = x
    if isinstance(out_tensor_list, list) and in_tensor_list is not None:
        n = (group or _world.default_group).nranks
        from ..ops.manipulation import split as _split

        out_tensor_list.clear()
        out_tensor_list.extend(_split(out, n, axis=0))
    return out


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    return all_to_all(out_tensor_list if out_tensor_list is not None
                      else in_tensor_list,
                      in_tensor_list if out_tensor_list is not None else None,
                      group=group, sync_op=sync_op)


def broadcast(tensor: Tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True):
    axes = _group_axes(group)
    if not in_spmd_region() or axes is None:
        return tensor
    out = _c_broadcast(tensor, axes=axes, src=int(src))
    tensor._value = out._value
    tensor._grad_node = out._grad_node
    tensor._out_idx = out._out_idx
    return tensor


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True):
    # SPMD model has no single-destination buffers; reduce == allreduce
    # with non-dst members free to ignore (XLA DCE removes unused copies).
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor: Tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op: bool = True):
    axes = _group_axes(group)
    if not in_spmd_region() or axes is None:
        if tensor_list:
            tensor._value = tensor_list[0]._value
        return tensor
    from ..ops.manipulation import concat as _concat, split as _split

    stacked = _concat(list(tensor_list), axis=0) if tensor_list else tensor
    stacked = _c_broadcast(stacked, axes=axes, src=int(src))
    n = (group or _world.default_group).nranks
    idx = axis_index(axes)
    chunk = stacked.shape[0] // n
    out = _dynamic_chunk(stacked, idx, chunk=chunk)
    tensor._value = out._value
    return tensor


@def_op("c_dynamic_chunk")
def _dynamic_chunk(x, idx, chunk=1):
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=0)


def ppermute(tensor: Tensor, perm: List[Tuple[int, int]],
             group: Optional[Group] = None):
    """Collective-permute: the TPU-native p2p primitive (ICI neighbor
    exchange). This is what pipeline send/recv lowers to (reference
    analog: fleet pp_utils/p2p_communication.py over NCCL send/recv)."""
    axes = _group_axes(group)
    if not in_spmd_region() or axes is None:
        return tensor
    return _c_ppermute(tensor, axes=axes, perm=tuple(tuple(p) for p in perm))


def send(tensor: Tensor, dst: int = 0, group: Optional[Group] = None,
         sync_op: bool = True):
    """Point-to-point send.

    Inside an SPMD region p2p is a *collective* — use
    :func:`ppermute` (which lowers to XLA collective-permute on ICI,
    the pipeline engine's p2p primitive). Eagerly (outside shard_map)
    this is a host-side transfer over the TCPStore/DCN — the role the
    reference's gloo send fills (process_group_gloo.cc).
    """
    if in_spmd_region():
        raise PreconditionNotMetError(
            "inside an SPMD region p2p is collective: express the "
            "send/recv pair as paddle_tpu.distributed.ppermute(tensor, "
            "perm=[(src, dst)])")
    from . import runtime as _rt

    val = np.asarray(tensor._value if isinstance(tensor, Tensor) else tensor)
    if not _rt.is_multiprocess():
        # world of 1: the only process is rank 0, so only a self-send can
        # ever be matched — reject anything else instead of buffering a
        # message no recv key will find
        enforce(int(dst) == 0,
                f"send(dst={dst}) in a single-process world: only "
                f"self-send (dst=0) is possible")
        _loopback.setdefault((0, 0), []).append(val)
        return _SendRecvTask(tensor)
    _rt.send_object(val, dst)
    return _SendRecvTask(tensor)


def recv(tensor: Tensor, src: int = 0, group: Optional[Group] = None,
         sync_op: bool = True):
    if in_spmd_region():
        raise PreconditionNotMetError(
            "inside an SPMD region p2p is collective: express the "
            "send/recv pair as paddle_tpu.distributed.ppermute(tensor, "
            "perm=[(src, dst)])")
    from . import runtime as _rt

    if not _rt.is_multiprocess():
        enforce(int(src) == 0,
                f"recv(src={src}) in a single-process world: only "
                f"self-recv (src=0) is possible")
        q = _loopback.get((0, 0))
        enforce(q, f"recv(src={src}): no matching send buffered "
                   f"(single-process loopback)")
        val = q.pop(0)
    else:
        val = _rt.recv_object(src)
    arr = jnp.asarray(val)
    if isinstance(tensor, Tensor):
        tensor._value = arr.astype(tensor._value.dtype).reshape(
            tensor._value.shape)
    return _SendRecvTask(tensor)


# single-process (src,dst) -> FIFO of pending sends, so a send/recv pair
# in a world of 1 still transfers the bytes instead of silently no-opping
_loopback: Dict[Tuple[int, int], List] = {}


class _SendRecvTask:
    """Completed-task handle (API parity with ProcessGroup::Task)."""

    def __init__(self, tensor):
        self.tensor = tensor

    def wait(self):
        return self.tensor

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


def barrier(group: Optional[Group] = None):
    if not in_spmd_region():
        from . import runtime as _rt

        # device flush + cross-process host barrier (reference: gloo
        # barrier in process_group_gloo.cc; here the TCPStore counter)
        jnp.zeros(()).block_until_ready()
        _rt.host_barrier("dist_barrier")
        return
    return None


def wait(tensor, group=None, use_calc_stream=True):
    return tensor


def all_gather_object(object_list, obj, group=None):
    """Gather picklable objects from every process (reference:
    python/paddle/distributed/communication/all_gather.py object path —
    gloo-backed; here pickled blobs through the TCPStore over DCN)."""
    from . import runtime as _rt

    object_list.extend(_rt.all_gather_object_host(obj))
    return object_list


def gather_object(obj, dst: int = 0, group=None):
    """Gather picklable objects on ``dst`` only (others get None) —
    the O(world)-at-root counterpart of all_gather_object."""
    from . import runtime as _rt

    return _rt.gather_object_host(obj, dst=dst)


def broadcast_object_list(object_list, src: int = 0, group=None):
    from . import runtime as _rt

    # one blob + one barrier for the whole list (not per element)
    object_list[:] = _rt.broadcast_object_host(list(object_list), src=src)
    return object_list


class stream:
    """paddle.distributed.stream.* parity namespace (the reference exposes
    stream-variant collectives; on TPU XLA owns streams so these are the
    same ops)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)


class P2POp:
    """One batched point-to-point operation (reference:
    communication/batch_isend_irecv.py P2POp): op is ``isend`` or
    ``irecv``, bound to a tensor and a peer rank."""

    def __init__(self, op, tensor, peer, group=None):
        enforce(op in (isend, irecv),
                "P2POp op must be paddle.distributed.isend or irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Issue a batch of isend/irecv (reference:
    communication/batch_isend_irecv.py). On TPU the sends/receives are
    XLA-ordered host-transport ops, so 'batching' is issuing them in
    list order; returns one task per op."""
    enforce(len(p2p_op_list) > 0, "batch_isend_irecv needs >= 1 P2POp")
    tasks = []
    for p in p2p_op_list:
        enforce(isinstance(p, P2POp),
                "batch_isend_irecv takes a list of P2POp")
        tasks.append(p.op(p.tensor, p.peer, p.group))
    return tasks
