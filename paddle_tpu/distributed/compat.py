"""Distributed API tail (reference: python/paddle/distributed/
__init__.py exports without a previous counterpart — aliases, semi-auto
helpers, enums, and gated PS-era entries).
"""
from __future__ import annotations

from ..core.enforce import enforce

__all__ = [
    "alltoall", "alltoall_single", "gather", "scatter_object_list",
    "destroy_process_group", "get_backend", "is_available",
    "is_initialized", "wait", "split", "spawn",
    "Strategy", "DistAttr", "ReduceType", "ParallelMode",
    "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "DistModel", "to_static", "shard_optimizer", "shard_scaler",
    "shard_dataloader", "unshard_dtensor",
    "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry",
    "load_state_dict", "save_state_dict", "gloo_init_parallel_env",
    "gloo_barrier", "gloo_release",
]


# -- collective aliases ----------------------------------------------------
def alltoall(out_tensor_list, in_tensor_list=None, group=None,
             sync_op=True):
    """(reference: communication/all_to_all.py alltoall). Matches the
    reference's out/in list order; also accepts (in, out) omitted form
    returning the list."""
    from .collective import all_to_all

    return all_to_all(out_tensor_list, in_tensor_list, group=group)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all_to_all: rows split across ranks (reference:
    communication/all_to_all.py alltoall_single) — expressed over the
    list form."""
    from . import get_world_size
    from ..ops.manipulation import concat, split as _split
    from .collective import all_to_all

    n = get_world_size()
    enforce(out_split_sizes is None,
            "uneven out_split_sizes are not supported here; pass None "
            "(equal splits) or use alltoall with explicit tensors")
    ins = _split(in_tensor, in_split_sizes
                 if in_split_sizes is not None else n, axis=0)
    outs = []
    all_to_all(outs, list(ins), group=group)
    result = concat(outs, axis=0)
    if out_tensor is not None and hasattr(out_tensor, "_value"):
        out_tensor._value = result._value
        return out_tensor
    return result


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather tensors to dst (reference: communication/gather.py) —
    built on all_gather; non-dst ranks receive nothing."""
    from . import get_rank
    from .collective import all_gather

    out = []
    all_gather(out, tensor, group=group)
    if get_rank() == dst and gather_list is not None:
        gather_list.extend(out)
    return out if get_rank() == dst else None


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """(reference: communication/scatter.py scatter_object_list) over
    the host object collectives."""
    from . import get_rank
    from .runtime import broadcast_object_host

    objs = broadcast_object_host(
        in_object_list if get_rank() == src else None, src=src)
    from . import get_world_size

    n = get_world_size()
    enforce(objs is not None and len(objs) % n == 0,
            lambda: f"scatter_object_list needs len(in_object_list) "
                    f"({len(objs or [])}) divisible by world size ({n})")
    per = len(objs) // n
    chunk = objs[get_rank() * per:(get_rank() + 1) * per]
    out_object_list.clear()
    out_object_list.extend(chunk)


def destroy_process_group(group=None):
    """(reference: collective.py destroy_process_group) — XLA owns
    communicators; host-side store state is released."""
    from . import runtime

    if hasattr(runtime, "shutdown"):
        runtime.shutdown()


def get_backend(group=None):
    return "XLA"  # the ICI/DCN collectives are XLA HLOs


def is_available():
    return True


def is_initialized():
    from . import collective

    return collective._world.default_group is not None


def wait(tensor, group=None, use_calc_stream=True):
    """(reference: collective.py wait) — XLA orders collectives by data
    dependence; block the host until the value is ready."""
    import jax

    v = tensor._value if hasattr(tensor, "_value") else tensor
    jax.block_until_ready(v)
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel split of an embedding/linear operation
    (reference: collective.py split -> mpu layers). Returns the
    corresponding parallel layer applied to x."""
    from .fleet.layers.mpu import (ColumnParallelLinear,
                                   RowParallelLinear,
                                   VocabParallelEmbedding)

    enforce(operation in ("linear", "embedding"),
            lambda: f"unsupported split operation {operation!r}")
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if axis == 0:
        layer = RowParallelLinear(size[0], size[1],
                                  weight_attr=weight_attr,
                                  has_bias=bias_attr is not False,
                                  input_is_parallel=False)
    else:
        layer = ColumnParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out)
    return layer(x)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """(reference: spawn.py) — fork nprocs processes running func(rank).
    The single-controller SPMD engine usually replaces this; provided
    for API parity with host-side workloads."""
    import multiprocessing as mp
    import os

    if nprocs <= 0:
        nprocs = int(os.environ.get("PADDLE_TPU_NPROCS", "1"))
    # fork: closures need no pickling and children inherit the env
    ctx = mp.get_context("fork")
    procs = []
    for rank in range(nprocs):
        def runner(r=rank):
            os.environ.update(PADDLE_TRAINER_ID=str(r),
                              PADDLE_TRAINERS_NUM=str(nprocs))
            func(*args)

        p = ctx.Process(target=runner, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs


# -- host (gloo-analog) helpers -------------------------------------------
def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """(reference: parallel_with_gloo.py) — the TCPStore-backed host
    collectives initialize through init_parallel_env here."""
    from . import init_parallel_env

    return init_parallel_env()


def gloo_barrier():
    from .runtime import host_barrier

    return host_barrier()


def gloo_release():
    return destroy_process_group()


# -- semi-auto helpers ------------------------------------------------------
class ReduceType:
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class ShardingStage1:
    """Marker for Strategy.sharding (reference: auto_parallel/api.py
    ShardingStage1)."""
    stage = 1


class ShardingStage2:
    stage = 2


class ShardingStage3:
    stage = 3


class DistAttr:
    """(reference: DistAttr — mesh + dims_mapping pair)."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs


class Strategy:
    """Semi-auto training strategy (reference: auto_parallel/api.py
    Strategy): knob container consumed by DistModel/to_static."""

    def __init__(self, config=None):
        config = config or {}
        self.sharding = config.get("sharding")
        self.fused_passes = config.get("fused_passes")
        self.gradient_merge = config.get("gradient_merge")
        self.pipeline = config.get("pipeline")


class DistModel:
    """(reference: auto_parallel/api.py DistModel — the to_static
    result): wraps the auto-parallel Engine's compiled step behind
    train()/eval()/predict() mode switches."""

    def __init__(self, layer, loader, loss=None, optimizer=None,
                 strategy=None):
        from . import fleet as _fleet
        from .auto_parallel.engine import Engine

        if _fleet.get_hybrid_communicate_group() is None:
            # default single-axis data-parallel mesh over all devices
            _fleet.init(is_collective=True)
        # adapt the reference's loss(out, label) contract to the
        # Engine's loss_fn(model, batch): the LAST batch element is the
        # label, the rest feed the model
        engine_loss = None
        if loss is not None:
            def engine_loss(m, batch):
                inputs = batch[:-1] if isinstance(batch, (tuple, list)) \
                    else (batch,)
                return loss(m(*inputs), batch[-1])
        self._engine = Engine(layer, loss_fn=engine_loss,
                              optimizer=optimizer)
        self._layer = layer
        self._loader = loader
        self._mode = "train"

    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def predict(self):
        self._mode = "predict"

    def __call__(self, *inputs):
        if self._mode == "train":
            batch = inputs[0] if len(inputs) == 1 else tuple(inputs)
            return self._engine.train_batch(batch)
        from ..autograd import no_grad

        with no_grad():
            return self._layer(*inputs)


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy=None):
    """(reference: auto_parallel/api.py to_static)."""
    return DistModel(layer, loader, loss=loss, optimizer=optimizer,
                     strategy=strategy)


def shard_optimizer(optimizer, shard_fn=None):
    """(reference: auto_parallel/api.py shard_optimizer) — with the
    ParallelEngine, optimizer states shard via the engine's ZeRO plan;
    this marks the optimizer for state sharding."""
    optimizer._shard_states = True
    return optimizer


def shard_scaler(scaler):
    """(reference: auto_parallel/api.py shard_scaler) — found_inf is
    already pmax-synced inside the compiled engine step."""
    return scaler


def shard_dataloader(dataloader, meshes=None, shard_dims=None,
                     is_dataset_splitted=False):
    """(reference: auto_parallel/api.py shard_dataloader) — the single-
    controller engine feeds global batches; per-mesh input sharding is
    applied by the engine, so the loader passes through."""
    return dataloader


def unshard_dtensor(dist_tensor):
    """Gather a sharded tensor to a replicated one (reference:
    auto_parallel/api.py unshard_dtensor)."""
    import jax

    from ..tensor import Tensor

    v = dist_tensor._value if isinstance(dist_tensor, Tensor) \
        else dist_tensor
    gathered = jax.device_get(v)
    out = Tensor(gathered,
                 stop_gradient=getattr(dist_tensor, "stop_gradient",
                                       True))
    out.dist_attr = None
    return out


# -- PS-era datasets (out of TPU scope; loud gates, reference:
#    fleet/dataset/dataset.py InMemoryDataset/QueueDataset) -----------------
def _ps_gate(name):
    raise NotImplementedError(
        f"{name} belongs to the brpc parameter-server data path, which "
        f"is out of scope for the TPU framework (SURVEY §7); use "
        f"paddle_tpu.io.DataLoader")


class InMemoryDataset:
    def __init__(self, *a, **k):
        _ps_gate("InMemoryDataset")


class QueueDataset:
    def __init__(self, *a, **k):
        _ps_gate("QueueDataset")


class CountFilterEntry:
    def __init__(self, *a, **k):
        _ps_gate("CountFilterEntry")


class ProbabilityEntry:
    def __init__(self, *a, **k):
        _ps_gate("ProbabilityEntry")


class ShowClickEntry:
    def __init__(self, *a, **k):
        _ps_gate("ShowClickEntry")


def load_state_dict(state_dict, path, **kw):
    from .checkpoint.load_state_dict import load_state_dict as _load

    return _load(state_dict, path, **kw)


def save_state_dict(state_dict, path, **kw):
    from .checkpoint.save_state_dict import save_state_dict as _save

    return _save(state_dict, path, **kw)
