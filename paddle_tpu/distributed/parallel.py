"""DataParallel + ParallelEnv.

(reference: python/paddle/distributed/parallel.py:395 DataParallel backed
by the C++ bucketed Reducer (fluid/imperative/reducer.h:129) with
comm/compute overlap. TPU-native: gradient sync is a psum over the 'dp'
mesh axes registered as a leaf-grad hook — inside the traced step XLA
schedules those psums concurrently with remaining backward compute, which
is exactly the overlap the bucketed Reducer implements by hand.)
"""
from __future__ import annotations

import os
from typing import Optional

from ..nn.layer import Layer
from ..tensor import Tensor
from . import collective as C

__all__ = ["DataParallel", "ParallelEnv"]


class ParallelEnv:
    """(reference: python/paddle/parallel.py ParallelEnv env block)."""

    @property
    def rank(self) -> int:
        return C.get_rank() if not C.in_spmd_region() else 0

    @property
    def world_size(self) -> int:
        return C.get_world_size()

    @property
    def device_id(self) -> int:
        return int(os.environ.get("FLAGS_selected_tpus", "0").split(",")[0])

    @property
    def current_endpoint(self) -> str:
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


class Reducer:
    """Bucketed fused gradient reduction (reference:
    fluid/imperative/reducer.h:129 — group_size buckets filled in
    reverse registration order; a bucket's allreduce fires the moment
    its last gradient arrives, overlapping with the rest of backward).

    TPU-native role: inside a compiled step XLA already fuses and
    overlaps the per-leaf psums, so this Reducer serves the EAGER
    multi-process path, where one fused host allreduce per ~25MB bucket
    replaces per-tensor round trips."""

    def __init__(self, params, group=None, comm_buffer_size_mb: float = 25.0,
                 find_unused_parameters: bool = False):
        import numpy as np

        self.group = group
        self._params = [p for p in params if p.trainable]
        self._enabled = True
        # reverse registration order: grads arrive roughly back-to-front.
        # find_unused_parameters: a param that never produces a grad
        # would leave its bucket pending forever, so degrade to
        # per-param buckets (each hook fires its own reduce — the
        # reference rebuilds buckets from the found-unused set instead)
        budget = 0 if find_unused_parameters else \
            comm_buffer_size_mb * (1 << 20)
        self._buckets = []
        cur, cur_bytes = [], 0
        for p in reversed(self._params):
            nbytes = int(np.prod(p._value.shape)) * p._value.dtype.itemsize
            if cur and cur_bytes + nbytes > budget:
                self._buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += nbytes
        if cur:
            self._buckets.append(cur)
        self._bucket_of = {id(p): bi
                           for bi, b in enumerate(self._buckets)
                           for p in b}
        self._pending = [dict() for _ in self._buckets]
        self.fused_reduce_count = 0  # observability (tests/tracing)

    @property
    def num_buckets(self) -> int:
        return len(self._buckets)

    def flush(self):
        """End-of-backward: reduce leftover partial buckets (reused
        params' late partials; buckets starved by grad-less params)."""
        if not self._enabled:
            for pend in self._pending:
                pend.clear()
            return
        for bi in range(len(self._buckets)):
            self._reduce_pending(bi)

    def hook_for(self, p):
        bi = self._bucket_of[id(p)]

        def hook(grad: Tensor) -> Tensor:
            if not self._enabled:
                return grad
            return self._arrive(bi, p, grad)

        return hook

    def _arrive(self, bi, p, grad: Tensor) -> Tensor:
        bucket = self._buckets[bi]
        pend = self._pending[bi]
        # ACCUMULATE: a reused parameter (tied weights) delivers several
        # partial grads per backward; reduction is linear, so partials
        # reduced in separate rounds still sum correctly
        prev = pend.get(id(p))
        pend[id(p)] = grad._value if prev is None else prev + grad._value
        if len(pend) < len(bucket):
            return grad  # provisional; swapped when the bucket fires
        # bucket complete: ONE fused allreduce over the flattened grads
        return self._reduce_pending(bi, p, grad._value)

    def _reduce_pending(self, bi, p=None, p_cur=None):
        """Fused-reduce whatever partials are pending in bucket ``bi``
        and swap them into the owners' .grad. Called on bucket
        completion and from the end-of-backward flush (which covers
        reused/unused-parameter leftovers)."""
        import jax.numpy as jnp

        pend = self._pending[bi]
        if not pend:
            return None
        bucket = [q for q in self._buckets[bi] if id(q) in pend]
        vals = [pend[id(q)] for q in bucket]
        flat = jnp.concatenate([v.reshape(-1).astype(jnp.float32)
                                for v in vals])
        red = C.all_reduce_mean_value(Tensor(flat, stop_gradient=True),
                                      group=self.group)
        rv = red._value if isinstance(red, Tensor) else red
        self.fused_reduce_count += 1
        off = 0
        out = None
        for q, v in zip(bucket, vals):
            n = v.size
            piece = rv[off:off + n].reshape(v.shape).astype(v.dtype)
            off += n
            if q is p:
                # hook return: the engine adds it onto p.grad, which
                # already holds any EARLIER provisional partials of p
                # from this pass (v - p_cur) — subtract them so the
                # reduced total lands exactly once
                prior = v - p_cur
                out = Tensor(piece - prior, stop_gradient=True)
            else:
                # q.grad currently holds prior-accumulation + this
                # pass's provisional local grad — swap only the
                # provisional part for its reduced value so no_sync /
                # multi-backward accumulation survives
                if q.grad is not None:
                    q.grad = Tensor(q.grad._value - v + piece,
                                    stop_gradient=True)
                else:
                    q.grad = Tensor(piece, stop_gradient=True)
        self._pending[bi] = {}
        return out


class DataParallel(Layer):
    """Wraps a model for data parallelism over the 'dp' axes of the mesh.

    grads are averaged across the group via a bucketed Reducer attached
    through leaf hooks (the reference's Reducer bucket callbacks,
    SURVEY.md §3.2 step 4)."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1, find_unused_parameters=False,
                 group: Optional[C.Group] = None):
        super().__init__()
        self._layers = layers
        self.group = group or C.get_group(0)
        self.find_unused_parameters = find_unused_parameters
        self._reducer = Reducer(
            layers.parameters(), group=self.group,
            comm_buffer_size_mb=comm_buffer_size,
            find_unused_parameters=find_unused_parameters)
        for p in layers.parameters():
            if p.trainable:
                p.register_hook(self._reducer.hook_for(p))
        # end-of-backward flush: reduces leftover partials (reused
        # params, buckets starved by grad-less params) — the reference
        # Reducer's finalize_backward
        from ..autograd.engine import register_backward_end_callback

        register_backward_end_callback(self._reducer.flush)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def no_sync(self):
        """Skip gradient sync inside the context (local accumulation —
        reference DataParallel.no_sync)."""
        import contextlib

        @contextlib.contextmanager
        def guard():
            self._reducer._enabled = False
            try:
                yield
            finally:
                self._reducer._enabled = True

        return guard()
