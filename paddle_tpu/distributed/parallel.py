"""DataParallel + ParallelEnv.

(reference: python/paddle/distributed/parallel.py:395 DataParallel backed
by the C++ bucketed Reducer (fluid/imperative/reducer.h:129) with
comm/compute overlap. TPU-native: gradient sync is a psum over the 'dp'
mesh axes registered as a leaf-grad hook — inside the traced step XLA
schedules those psums concurrently with remaining backward compute, which
is exactly the overlap the bucketed Reducer implements by hand.)
"""
from __future__ import annotations

import os
from typing import Optional

from ..nn.layer import Layer
from ..tensor import Tensor
from . import collective as C

__all__ = ["DataParallel", "ParallelEnv"]


class ParallelEnv:
    """(reference: python/paddle/parallel.py ParallelEnv env block)."""

    @property
    def rank(self) -> int:
        return C.get_rank() if not C.in_spmd_region() else 0

    @property
    def world_size(self) -> int:
        return C.get_world_size()

    @property
    def device_id(self) -> int:
        return int(os.environ.get("FLAGS_selected_tpus", "0").split(",")[0])

    @property
    def current_endpoint(self) -> str:
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


class DataParallel(Layer):
    """Wraps a model for data parallelism over the 'dp' axes of the mesh.

    grads are averaged across the group via leaf hooks at grad-accumulation
    time (the reference's Reducer bucket callbacks, SURVEY.md §3.2 step 4).
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1, find_unused_parameters=False,
                 group: Optional[C.Group] = None):
        super().__init__()
        self._layers = layers
        self.group = group or C.get_group(0)
        self.find_unused_parameters = find_unused_parameters
        if C.get_world_size(self.group) > 1 or True:
            self._register_grad_hooks()

    def _register_grad_hooks(self):
        group = self.group

        def make_hook():
            def hook(grad: Tensor) -> Tensor:
                return C.all_reduce_mean_value(grad, group=group)

            return hook

        for p in self._layers.parameters():
            if p.trainable:
                p.register_hook(make_hook())

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            yield

        return guard()
