"""Host-memory offload tier: model state out of HBM, prefetched back
just-in-time.

ZeRO stage 3 (grad_buckets.py) cut per-device model-state bytes to
1/sharding_degree; this module buys the next order of magnitude by
moving whole state classes across the HBM->host boundary between
steps. Optimizer moments, AMP master weights, and quant-comm
error-feedback residuals (optionally the stored param shards too) live
in host memory while the device computes, and are re-placed at their
exact live sharding right before the next optimizer step:

- **What lives where.** Between steps an offloaded array exists only
  as a :class:`HostState`: one host ``np`` buffer per addressable
  shard plus the ``jax`` sharding needed to rebuild the global array.
  On backends with a pinned-host memory space the buffers ride a
  ``device_put`` with the sharding's ``pinned_host`` memory kind
  instead (same API, zero-copy DMA on real chips); CPU smoke uses the
  ``np`` path. The round trip is bit-exact by construction — bytes are
  copied, never re-derived — which is what makes offload-on vs
  offload-off loss curves identical (pinned by tests/bench).

- **Bucketed just-in-time prefetch.** Slots are grouped by the SAME
  signature buckets the grad reduce-scatter / stage-3 gather use
  (``BucketPlan``; seam groups keep their ``g<i>`` name, flat buckets
  ``g<i>b<j>``, plan-less engines one ``flat`` bucket), and the
  prefetch walks buckets in plan order at step dispatch — the
  ``offload.prefetch`` failpoint fires once per bucket, so crash tests
  can SIGKILL mid-prefetch deterministically. ``prefetch_buckets`` > 0
  warms that many leading buckets on a background thread right after
  the previous step's page-out, overlapping the host DMA with the
  inter-step host work (the thread only fills a lock-guarded staging
  dict; the dispatcher joins it before consuming — no donation-reuse,
  no blocking call under the lock).

- **First-class accounting.** Every transfer is booked at its closed
  form — the per-device addressable-shard bytes
  (``memledger.shard_bytes``) per slot, summed per bucket — into the
  ``paddle_tpu_offload_*`` gauges; prefetch wall seconds are journaled
  as an OVERLAPPED goodput segment (like the async checkpoint writer);
  ``memledger.account_engine`` books host-resident bytes under a
  ``host_state`` component that the analytic
  ``closed_form_state_bytes`` cross-checks byte-for-byte.

Knob surface (the reference ``sharding_configs`` dict)::

    strategy.hybrid_configs["sharding_configs"]["offload"] = {
        "optimizer": True,        # moments + masters + EF residuals
        "params": False,          # stored param shards too (stage 3)
        "prefetch_buckets": 2,    # background-warmed leading buckets
    }

The serving engine reuses the same tier shape for cold KV pages
(inference/serving.py): LRU-idle prefix-cache pages spill their
payload to host on eviction and fault back through the normal page
allocation on a prefix hit, charged to the same transfer gauges with
``component="kv_page"``.
"""
from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from . import failpoints as _fp

__all__ = ["OffloadConfig", "offload_config", "make_config", "make_tier",
           "HostState", "is_host", "page_out", "place", "OffloadTier",
           "host_shard_bytes"]


# ---------------------------------------------------------------------------
# knob surface
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class OffloadConfig:
    """Parsed ``sharding_configs["offload"]`` sub-config."""

    optimizer: bool = True       # moments + AMP masters + EF residuals
    params: bool = False         # stored param shards (stage-3 style)
    prefetch_buckets: int = 0    # buckets warmed on the background thread

    @property
    def enabled(self) -> bool:
        return self.optimizer or self.params


def make_config(off) -> Optional[OffloadConfig]:
    """Normalize a knob value (dict / True / OffloadConfig / falsy)."""
    if not off:
        return None
    if isinstance(off, OffloadConfig):
        return off if off.enabled else None
    if off is True:
        off = {}
    cfg = OffloadConfig(
        optimizer=bool(off.get("optimizer", True)),
        params=bool(off.get("params", False)),
        prefetch_buckets=int(off.get("prefetch_buckets", 0)))
    return cfg if cfg.enabled else None


def offload_config(strategy=None) -> Optional[OffloadConfig]:
    """The active fleet strategy's ``sharding_configs["offload"]``
    sub-config (None when absent) — same knob-parser shape as
    ``grad_buckets.strategy_config`` / ``stage_config``."""
    if strategy is None:
        from . import fleet as _fleet

        strategy = _fleet.get_strategy()
    if strategy is None:
        return None
    sc = strategy.hybrid_configs.get("sharding_configs") or {}
    return make_config(sc.get("offload"))


def make_tier(off, mesh=None) -> Optional["OffloadTier"]:
    cfg = make_config(off)
    return OffloadTier(cfg, mesh) if cfg is not None else None


# ---------------------------------------------------------------------------
# the host-resident form of one array
# ---------------------------------------------------------------------------
def _pinned_host_sharding(sharding):
    """The same sharding placed in the pinned-host memory space, or
    None when the backend has no such space (CPU smoke)."""
    try:
        dev = next(iter(sharding.device_set))
        kinds = {m.kind for m in dev.addressable_memories()}
        if "pinned_host" not in kinds:
            return None
        return sharding.with_memory_kind("pinned_host")
    except Exception:
        return None


class HostState:
    """One offloaded array: this process's addressable shards as host
    buffers plus the sharding needed to rebuild the global ``jax.Array``
    bit-exactly. Treated as an immutable value everywhere (snapshots
    share it; ``place`` builds fresh device arrays)."""

    __slots__ = ("shape", "dtype", "_sharding", "_shards", "_harr")

    def __init__(self, shape, dtype, sharding, shards, harr=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self._sharding = sharding
        self._shards = shards    # tuple of (device, np.ndarray) or None
        self._harr = harr        # pinned-host jax.Array (TPU path) or None

    @property
    def sharding(self):
        # exposed so memledger.shard_bytes computes the per-device
        # shard size of a HostState exactly like a live jax.Array
        return self._sharding

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def nbytes(self) -> int:
        """Total host bytes this process holds (every addressable
        shard, replication included — the actual RAM cost)."""
        if self._shards is not None:
            return int(sum(b.nbytes for _, b in self._shards))
        return int(np.prod(self.shape) if self.shape else 1) \
            * int(self.dtype.itemsize)

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"HostState(shape={self.shape}, dtype={self.dtype}, "
                f"shards={len(self._shards or ())})")


def is_host(v) -> bool:
    return isinstance(v, HostState)


def page_out(arr) -> HostState:
    """Move ``arr`` to the host tier: per-addressable-shard host
    copies (or one pinned-host ``device_put`` where the backend has
    that memory space), preserving the sharding for an exact
    round-trip. The device buffers are released with the last
    reference to ``arr``."""
    sharding = arr.sharding
    hshard = _pinned_host_sharding(sharding)
    if hshard is not None:
        harr = jax.device_put(arr, hshard)
        return HostState(arr.shape, arr.dtype, sharding, None, harr)
    shards = tuple((s.device, np.asarray(s.data))
                   for s in arr.addressable_shards)
    return HostState(arr.shape, arr.dtype, sharding, shards)


def place(hs: HostState) -> jax.Array:
    """Rebuild the global device array from a :class:`HostState` at
    its original sharding — the bit-exact inverse of ``page_out``."""
    if hs._harr is not None:
        return jax.device_put(hs._harr, hs._sharding)
    if len(hs._shards) == 1 and hs._shards[0][1].shape == hs.shape:
        # single-shard fast path (also covers plan-less 1-device runs)
        return jax.device_put(hs._shards[0][1], hs._sharding)
    bufs = [jax.device_put(b, d) for d, b in hs._shards]
    return jax.make_array_from_single_device_arrays(
        hs.shape, hs._sharding, bufs)


def host_shard_bytes(v) -> int:
    """Closed-form per-device shard bytes of one slot (live array or
    HostState) — the unit every transfer-ledger entry is booked at."""
    from ..observability.memledger import shard_bytes

    return shard_bytes(v)


# ---------------------------------------------------------------------------
# the engine-side tier
# ---------------------------------------------------------------------------
class OffloadTier:
    """Owns the host tier of one ``ParallelEngine``: which state slots
    offload, their bucket grouping, the background prefetch thread,
    and the transfer ledger / gauges. All mutation happens on the
    train-loop thread except the staging dict the prefetch worker
    fills, which is guarded by ``_lock``."""

    def __init__(self, cfg: OffloadConfig, mesh=None):
        from ..observability.catalog import offload_metrics

        self.cfg = cfg
        self.mesh = mesh
        self._metrics = offload_metrics()
        self._plan = None
        self._plan_built = False
        self._bucket_of: Dict[int, str] = {}   # trainable index -> name
        self._bucket_order: Dict[str, int] = {}
        # cumulative closed-form transfer ledger, (component, direction)
        self._bytes: Dict[Tuple[str, str], int] = {}
        self._ops: Dict[Tuple[str, str], int] = {}
        self._host_bytes: Dict[str, int] = {}  # per-device shard bytes
        self._last_prefetch_s = 0.0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._staged: Dict[Any, Any] = {}      # slot key -> device array

    # -- bucket naming (the BucketPlan discipline) -----------------------
    def _ensure_plan(self, engine) -> None:
        if self._plan_built:
            return
        self._plan_built = True
        plan = engine._build_bucket_plan()
        self._plan = plan
        order: List[str] = []
        if plan is not None:
            for gi, g in enumerate(plan.groups):
                if g.seam:
                    name = f"g{gi}"
                    order.append(name)
                    for e in g.entries:
                        self._bucket_of[e.index] = name
                else:
                    for bi, bucket in enumerate(g.buckets):
                        name = f"g{gi}b{bi}"
                        order.append(name)
                        for e in bucket:
                            self._bucket_of[e.index] = name
        order.append("flat")     # plan-less slots / uncovered tail
        self._bucket_order = {n: i for i, n in enumerate(order)}

    def _bucket_name(self, t_index: Optional[int]) -> str:
        if t_index is None:
            return "flat"
        return self._bucket_of.get(t_index, "flat")

    # -- slot enumeration ------------------------------------------------
    def _iter_slots(self, engine) -> Iterator[Tuple[Any, str, str]]:
        """Every offloadable slot as (key, component, bucket). Keys are
        stable across steps/restores: trainable index + state leaf name
        (never ``id()`` — params rebind on donation writeback)."""
        opt = engine.optimizer
        if self.cfg.optimizer and opt is not None:
            for ti, p in enumerate(engine.trainable):
                bucket = self._bucket_name(ti)
                st = opt._states.get(id(p))
                for k in (st or {}):
                    yield ("s", ti, k), "optimizer_state", bucket
                if id(p) in opt._master_weights:
                    yield ("m", ti), "master_weights", bucket
            for name in getattr(engine, "_quant_residuals", {}):
                bucket = name if name in self._bucket_order else "flat"
                yield ("q", name), "quant_residual", bucket
        if self.cfg.params:
            t_of = {id(p): i for i, p in enumerate(engine.trainable)}
            for pi, p in enumerate(engine.params):
                bucket = self._bucket_name(t_of.get(id(p)))
                yield ("p", pi), "params", bucket

    @staticmethod
    def _get(engine, key):
        kind = key[0]
        if kind == "s":
            p = engine.trainable[key[1]]
            return engine.optimizer._states[id(p)].get(key[2])
        if kind == "m":
            p = engine.trainable[key[1]]
            return engine.optimizer._master_weights.get(id(p))
        if kind == "q":
            return engine._quant_residuals.get(key[1])
        return engine.params[key[1]]._value

    @staticmethod
    def _set(engine, key, val) -> None:
        kind = key[0]
        if kind == "s":
            p = engine.trainable[key[1]]
            engine.optimizer._states[id(p)][key[2]] = val
        elif kind == "m":
            p = engine.trainable[key[1]]
            engine.optimizer._master_weights[id(p)] = val
        elif kind == "q":
            engine._quant_residuals[key[1]] = val
        else:
            engine.params[key[1]]._value = val

    # -- transfer ledger -------------------------------------------------
    def _note(self, component: str, direction: str, nbytes: int) -> None:
        k = (component, direction)
        self._bytes[k] = self._bytes.get(k, 0) + int(nbytes)
        self._ops[k] = self._ops.get(k, 0) + 1

    def transfer_bytes(self, component: Optional[str] = None,
                       direction: Optional[str] = None) -> int:
        """Cumulative closed-form transfer bytes, optionally filtered —
        what the bench lines pin against the analytic form."""
        return sum(v for (c, d), v in self._bytes.items()
                   if (component is None or c == component)
                   and (direction is None or d == direction))

    def host_resident_bytes(self, component: Optional[str] = None) -> int:
        return sum(v for c, v in self._host_bytes.items()
                   if component is None or c == component)

    def publish(self) -> None:
        m = self._metrics
        for (c, d), v in self._bytes.items():
            m["bytes"].set(float(v), component=c, direction=d)
        for (c, d), v in self._ops.items():
            m["ops"].set(float(v), component=c, direction=d)
        for c, v in self._host_bytes.items():
            m["host"].set(float(v), component=c)
        m["prefetch_seconds"].set(self._last_prefetch_s)

    # -- page-out / prefetch ---------------------------------------------
    def page_out_step(self, engine, spawn: bool = True) -> None:
        """Move every offloadable slot that is device-resident to the
        host tier (after the step's writeback — the fresh output
        arrays, never the donated inputs), then optionally warm the
        first ``prefetch_buckets`` buckets on the background thread."""
        self._ensure_plan(engine)
        self._drain_thread()
        book = not getattr(engine, "_profiling", False)
        for key, comp, _bucket in self._iter_slots(engine):
            v = self._get(engine, key)
            if v is None or is_host(v) or not isinstance(v, jax.Array):
                continue
            b = host_shard_bytes(v)
            self._set(engine, key, page_out(v))
            self._host_bytes[comp] = self._host_bytes.get(comp, 0) + b
            if book:
                self._note(comp, "d2h", b)
        if book:
            self.publish()
        if spawn and self.cfg.prefetch_buckets > 0:
            self._spawn_prefetch(engine)

    def _bucketed_host_slots(self, engine):
        """Host-resident slots grouped by bucket in plan order."""
        grouped: Dict[str, List[Tuple[Any, str]]] = {}
        for key, comp, bucket in self._iter_slots(engine):
            v = self._get(engine, key)
            if is_host(v):
                grouped.setdefault(bucket, []).append((key, comp))
        last = len(self._bucket_order)
        return sorted(grouped.items(),
                      key=lambda kv: self._bucket_order.get(kv[0], last))

    def _spawn_prefetch(self, engine) -> None:
        buckets = self._bucketed_host_slots(engine)
        items: List[Tuple[Any, HostState]] = []
        for _name, entries in buckets[:self.cfg.prefetch_buckets]:
            for key, _comp in entries:
                items.append((key, self._get(engine, key)))
        if not items:
            return

        def worker(items=items):
            for key, hs in items:
                arr = place(hs)
                with self._lock:
                    self._staged[key] = arr

        # non-daemon: a daemon thread mid-device_put at interpreter exit
        # aborts the XLA runtime teardown; the worker is one short
        # device_put pass, so letting exit wait for it is cheap
        self._thread = threading.Thread(
            target=worker, daemon=False, name="offload-prefetch")
        self._thread.start()

    def _drain_thread(self) -> Dict[Any, Any]:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        with self._lock:
            staged, self._staged = self._staged, {}
        return staged

    def prefetch_step(self, engine) -> None:
        """Materialize every host-resident slot at its live sharding,
        bucket by bucket in plan order, right before the compiled step
        dispatch. Fires the ``offload.prefetch`` failpoint once per
        bucket (crash tests SIGKILL here); the wall window is journaled
        as an OVERLAPPED goodput segment like the async checkpoint
        writer's commits."""
        from ..observability import goodput as _gp

        self._ensure_plan(engine)
        t0 = time.perf_counter()
        w0 = time.time()
        staged = self._drain_thread()
        book = not getattr(engine, "_profiling", False)
        for _name, entries in self._bucketed_host_slots(engine):
            _fp.hit("offload.prefetch")
            for key, comp in entries:
                hs = self._get(engine, key)
                b = host_shard_bytes(hs)
                arr = staged.pop(key, None)
                if arr is None:
                    arr = place(hs)
                self._set(engine, key, arr)
                self._host_bytes[comp] = \
                    self._host_bytes.get(comp, 0) - b
                if book:
                    self._note(comp, "h2d", b)
        self._last_prefetch_s = time.perf_counter() - t0
        if book:
            led = _gp.current()
            if led is not None:
                led.record_overlapped("offload_prefetch", w0,
                                      time.time())
            self.publish()

    # -- whole-tier residency (checkpoint / eval / analysis) -------------
    def restore_device(self, engine) -> None:
        """Materialize EVERY host slot (no failpoint, no overlap
        booking — callers stall on purpose: checkpoint snapshots, state
        loads, eval gathers, AOT memory analysis)."""
        self._ensure_plan(engine)
        staged = self._drain_thread()
        book = not getattr(engine, "_profiling", False)
        for _name, entries in self._bucketed_host_slots(engine):
            for key, comp in entries:
                hs = self._get(engine, key)
                b = host_shard_bytes(hs)
                arr = staged.pop(key, None)
                if arr is None:
                    arr = place(hs)
                self._set(engine, key, arr)
                self._host_bytes[comp] = \
                    self._host_bytes.get(comp, 0) - b
                if book:
                    self._note(comp, "h2d", b)
        if book:
            self.publish()

    def restore_params(self, engine) -> None:
        """Materialize host-resident PARAM slots only — eval paths read
        ``p._value`` directly; the params page back out at the next
        train step's page-out."""
        if not self.cfg.params:
            return
        self._ensure_plan(engine)
        staged = self._drain_thread()
        for key, comp, _bucket in self._iter_slots(engine):
            if key[0] != "p":
                continue
            hs = self._get(engine, key)
            if not is_host(hs):
                continue
            b = host_shard_bytes(hs)
            arr = staged.pop(key, None)
            if arr is None:
                arr = place(hs)
            self._set(engine, key, arr)
            self._note(comp, "h2d", b)
            self._host_bytes[comp] = self._host_bytes.get(comp, 0) - b
        with self._lock:
            # warmed non-param slots stay staged for the next prefetch
            for key, arr in staged.items():
                self._staged.setdefault(key, arr)
        self.publish()

    @contextlib.contextmanager
    def resident(self, engine):
        """Device-resident window: everything paged in on entry, back
        out on exit (no background warm — the caller decides when the
        next step's prefetch starts)."""
        self.restore_device(engine)
        try:
            yield
        finally:
            self.page_out_step(engine, spawn=False)
