"""Deterministic failpoints: named fault-injection sites for
crash-consistency testing.

Production fault tolerance is only as real as the faults it has been
tested against, so the checkpoint writer, the TCPStore client, and the
engine's step dispatch each carry *named* failpoints — inert no-ops in
normal operation (one dict lookup against an empty table) that tests or
an operator can arm to raise, hang, corrupt bytes, or SIGKILL the
process at exactly that point:

    PADDLE_TPU_FAILPOINTS="ckpt.write_shard=raise@2;store.set=hang"

Spec grammar (';'-separated entries)::

    <name>=<action>[@<n>]

- ``name``: the failpoint site (see ``KNOWN_SITES``); arbitrary names
  are allowed so tests can add their own sites.
- ``action``: ``raise`` (FailpointError), ``hang`` (sleep, default 3600s
  — the watchdog's prey; ``hang:<seconds>`` overrides), ``corrupt``
  (flip bits in the bytes passing through the site — only meaningful at
  sites that move a payload), ``kill`` (SIGKILL this process: the
  crash-consistency hammer — no atexit, no flushes, exactly like a
  preemption).
- ``@n``: trigger on the n-th hit of the site (1-based) and every hit
  after it; omitted = every hit from the first.

Sites fire via :func:`hit`::

    data = failpoints.hit("ckpt.write_shard", data)   # may raise/kill
    failpoints.hit("store.set")                       # payload-less

Tests prefer the scoped form so one test can never leak an armed
failpoint into the next::

    with failpoints.scoped("ckpt.commit=raise"):
        ...

The table is process-global and read at module import from
``PADDLE_TPU_FAILPOINTS`` (so a subprocess worker is armed by its
environment alone — the SIGKILL integration tests need nothing else).
"""
from __future__ import annotations

import contextlib
import os
import signal
import threading
import time
from typing import Dict, Optional

__all__ = ["FailpointError", "configure", "clear", "scoped", "hit",
           "active", "hit_count", "KNOWN_SITES"]

ENV_VAR = "PADDLE_TPU_FAILPOINTS"

# the instrumented sites shipped in-tree (arbitrary names also work)
KNOWN_SITES = (
    "ckpt.write_shard",     # per-shard npz write (payload: shard bytes)
    "ckpt.write_metadata",  # metadata json write (payload: json bytes)
    "ckpt.commit",          # just before the COMMIT marker is written
    "ckpt.rename",          # just before tmp -> final atomic rename
    "store.set",            # TCPStore.set
    "store.get",            # TCPStore.get
    "engine.step_dispatch",  # ParallelEngine step entry
    "offload.prefetch",     # host-offload per-bucket prefetch (one hit
                            # per bucket per dispatch; host_offload.py)
    # telemetry-only loss perturbation: arm with action "corrupt"
    # (e.g. "health.loss_spike=corrupt@12") to make the health
    # monitor's N-th OBSERVED loss a spike — training state is
    # untouched (observability/healthmon.py)
    "health.loss_spike",
)

_ACTIONS = ("raise", "hang", "corrupt", "kill")


class FailpointError(RuntimeError):
    """Raised by an armed ``raise`` failpoint."""


class _Point:
    __slots__ = ("action", "after", "hangs", "hits")

    def __init__(self, action: str, after: int = 1, hangs: float = 3600.0):
        self.action = action
        self.after = after
        self.hangs = hangs
        self.hits = 0


_lock = threading.Lock()
_points: Dict[str, _Point] = {}
# fast path: hit() checks this bool before taking the lock, so an
# unarmed process pays one attribute read per site
_armed = False


def _parse(spec: str) -> Dict[str, _Point]:
    out: Dict[str, _Point] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rhs = entry.partition("=")
        if not sep or not name.strip():
            raise ValueError(
                f"failpoint entry {entry!r}: expected <name>=<action>[@n]")
        action, _, after_s = rhs.partition("@")
        action = action.strip()
        hangs = 3600.0
        if action.startswith("hang:"):
            hangs = float(action.split(":", 1)[1])
            action = "hang"
        if action not in _ACTIONS:
            raise ValueError(
                f"failpoint {name.strip()!r}: unknown action {action!r} "
                f"(choose from {', '.join(_ACTIONS)})")
        after = int(after_s) if after_s else 1
        if after < 1:
            raise ValueError(
                f"failpoint {name.strip()!r}: @{after} must be >= 1 "
                "(1-based hit count)")
        out[name.strip()] = _Point(action, after, hangs)
    return out


def configure(spec: str) -> None:
    """Arm the failpoint table from a spec string (replaces the current
    table; hit counters reset)."""
    global _armed
    pts = _parse(spec)
    with _lock:
        _points.clear()
        _points.update(pts)
        _armed = bool(_points)


def clear() -> None:
    """Disarm every failpoint."""
    global _armed
    with _lock:
        _points.clear()
        _armed = False


@contextlib.contextmanager
def scoped(spec: str):
    """Arm ``spec`` for the duration of the block, then restore the
    previous table (counters of surviving points reset)."""
    with _lock:
        prev = dict(_points)
    configure(spec)
    try:
        yield
    finally:
        global _armed
        with _lock:
            _points.clear()
            _points.update(prev)
            _armed = bool(_points)


def active(name: str) -> bool:
    """Whether ``name`` is armed (regardless of hit count)."""
    if not _armed:
        return False
    with _lock:
        return name in _points


def hit_count(name: str) -> int:
    """How many times site ``name`` has fired hit() so far."""
    with _lock:
        p = _points.get(name)
        return p.hits if p is not None else 0


def _corrupt(data: bytes) -> bytes:
    """Flip bits across the payload (start, middle, end) so any honest
    checksum catches it regardless of where the reader looks."""
    if not data:
        return data
    buf = bytearray(data)
    for idx in {0, len(buf) // 2, len(buf) - 1}:
        buf[idx] ^= 0xFF
    return bytes(buf)


def hit(name: str, data: Optional[bytes] = None) -> Optional[bytes]:
    """Fire failpoint site ``name``.

    Unarmed: returns ``data`` untouched (the common case — one bool
    read). Armed and at/past its ``@n`` trigger: performs the action.
    ``corrupt`` returns mangled bytes; the other actions never return
    normally (raise / sleep / SIGKILL).
    """
    if not _armed:
        return data
    with _lock:
        p = _points.get(name)
        if p is None:
            return data
        p.hits += 1
        if p.hits < p.after:
            return data
        action, hangs = p.action, p.hangs
    if action == "raise":
        raise FailpointError(f"failpoint {name!r} armed (hit "
                             f"{hit_count(name)})")
    if action == "hang":
        time.sleep(hangs)
        return data
    if action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)          # unreachable; SIGKILL delivery is async
    return _corrupt(data) if data is not None else data


# subprocess workers arm themselves from the environment alone
if os.environ.get(ENV_VAR):
    configure(os.environ[ENV_VAR])
