"""Bucketed bidirectional collectives: T3-style eager per-bucket grad
scatter in backward AND just-in-time ZeRO-3 param gather in forward.

The engine's unbucketed step computes the ENTIRE backward and only then
issues one collective per parameter — so every step ends with a fully
exposed grad-sync tail (the ``grad_sync_exposed_seconds`` the exposed-
comm attribution in observability/commledger.py measures). T3
(Transparent Tracking & Triggering, PAPERS.md) hides that tail by
fusing each producing compute step with its collective. This module is
the static *plan* for that restructuring:

- **Flat models** (no stacked pipeline middle): trainable parameters are
  grouped by *sync signature* (the exact collective set their grads
  need: pmean axes, extra psum axes, duplication rescale, the ZeRO
  reduce-scatter entry, dtype, and the grad-norm psum axes) and each
  group is cut into size-targeted buckets in REVERSE registration order
  — the tape forms grads last-layer-first, so issuing bucket i's
  coalesced collective as its own dataflow node (depending only on that
  bucket's grads) lets XLA's latency-hiding scheduler start it while
  bucket i+1's backward compute is still running.
- **Pipelined models**: the PR-5 stacked-params chunk layout IS the
  bucketing seam (``PipelineLayer.grad_bucket_seam``). The stacked
  grads leave the pipeline vjp as ``[rows, ...]`` arrays; the sync runs
  as a ``lax.scan`` over row chunks with the per-bucket reduce-scatter
  / pmean issued inside each tick, so one monolithic end-of-step
  collective becomes ``nb`` pipelined chunk collectives (XLA's async
  collectives overlap tick i's wire time with tick i+1's pack/unpack).
  Ledger records inside the scan carry ``trips=nb``
  (commledger.scan_trips) so byte/op accounting stays EXACT.

Coalescing is bit-exact: a bucket's grads are packed into one flat
buffer — *rank-major* for the reduce-scatter path, so
``psum_scatter(flat)`` hands every rank exactly the concatenation of
the per-parameter shards the unbucketed path would have produced —
and psum/pmean/reduce-scatter are elementwise across ranks, so the
synced values are identical to the per-parameter collectives
regardless of grouping (tests pin loss/param parity and exact wire
bytes: sum over buckets == the unbucketed closed form).

**Stage-3 just-in-time gather** (the bidirectional half): under
``sharding_configs["sharding_stage"] = 3`` parameters are STORED
shard-only (engine._ZeroPlan ``store_sharded``) and
:meth:`BucketPlan.gather` re-materializes them at forward entry through
the SAME signature buckets the backward scatters grads through — one
coalesced flat ``all_gather`` per flat bucket (rank-major inverse
unpack, bit-exact vs the per-parameter tiled gather), and a
``lax.scan`` over the seam group's nb row ticks for the pp
stacked-params chunks, noted under ``commledger.scan_trips(nb)`` so
the gather's wire bytes stay trips-exact like the grad scan's. The
collective itself is the :func:`stage3_gather` ``jax.custom_vjp``
whose backward is the mirrored ledger-shimmed reduce-scatter
(all_gather ↔ reduce_scatter — the tpulint vjp-ledger-symmetry
pairing), so anything that differentiates through a gathered value
scatters its cotangent inside the ledger. quant_comm's ``param_gather``
composes per bucket: the packed int8 payload + bf16 scales go on the
wire once and each rank splices its OWN exact flat shard back over its
block, so the authoritative shard state never sees compression noise
(quant_comm.quantized_param_gather discipline, at bucket grain).

Knob (reference surface: sharding comm_overlap / comm_buffer_size_MB,
dygraph_sharding_optimizer buffer fusion):
``strategy.hybrid_configs["sharding_configs"]["comm_overlap"]`` with
``comm_buffer_size_MB`` sizing the per-bucket payload; default off.
``sharding_stage`` / ``stage3_release_after_forward`` (read via
``stage_config``) drive the stage-3 storage + gather grain.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..observability import commledger as _cl

__all__ = ["BucketPlan", "build_plan", "strategy_config", "stage_config",
           "stage3_gather", "DEFAULT_BUFFER_MB"]

# same default as the eager DataParallel reducer (parallel.py): the
# reference's fuse-buffer size
DEFAULT_BUFFER_MB = 25.0


def strategy_config(strategy=None) -> Tuple[bool, float]:
    """(comm_overlap, comm_buffer_size_MB) from the active fleet
    strategy's ``hybrid_configs["sharding_configs"]`` (the reference
    knob surface), or the defaults when no strategy is active."""
    if strategy is None:
        from . import fleet as _fleet

        strategy = _fleet.get_strategy()
    if strategy is None:
        return False, DEFAULT_BUFFER_MB
    sc = strategy.hybrid_configs.get("sharding_configs") or {}
    return (bool(sc.get("comm_overlap", False)),
            float(sc.get("comm_buffer_size_MB", DEFAULT_BUFFER_MB)))


def stage_config(strategy=None) -> Tuple[int, bool]:
    """(sharding_stage, stage3_release_after_forward) from the active
    fleet strategy's ``hybrid_configs["sharding_configs"]`` (the
    reference group-sharded level surface: 1/2 = os/os_g, 3 = p_g_os
    shard-only parameter storage); (2, True) with no strategy."""
    if strategy is None:
        from . import fleet as _fleet

        strategy = _fleet.get_strategy()
    if strategy is None:
        return 2, True
    sc = strategy.hybrid_configs.get("sharding_configs") or {}
    return (int(sc.get("sharding_stage", 2)),
            bool(sc.get("stage3_release_after_forward", True)))


# ---------------------------------------------------------------------------
# the static plan
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BucketEntry:
    """One parameter's slot in a bucket (static metadata only)."""

    pid: int                     # id(param) — runtime key, not hashed
    index: int                   # position in `trainable` (stable key)
    shape: Tuple[int, ...]       # LOCAL grad shape inside the step
    dtype: str
    shard_dim: Optional[int]     # ZeRO scatter dim (local coords)
    row_dims: int                # leading stacked-layer dims (seam)
    stored: bool = False         # stage-3: param STORED shard-only, the
    #                              forward gathers it through this bucket

    def describe(self) -> Tuple:
        return (self.index, self.shape, self.dtype, self.shard_dim,
                self.row_dims, self.stored)


@dataclass
class BucketGroup:
    """Parameters sharing one sync signature; cut into buckets."""

    kind: str                    # "rs" (ZeRO reduce-scatter) | "pmean"
    seam: bool                   # stacked-layer scan group?
    pm: Tuple[str, ...]          # grad-mean axes (dp_only for "rs")
    extra: Tuple[str, ...]       # extra psum axes (pp ownership, sp)
    dup: int                     # data-axis duplication rescale
    n: int                       # ZeRO group size ("rs")
    axis: Optional[str]          # ZeRO axis ("rs")
    dtype: str
    gnorm_axes: Tuple[str, ...]  # folded grad-norm psum axes
    entries: List[BucketEntry] = field(default_factory=list)
    buckets: List[List[BucketEntry]] = field(default_factory=list)
    # seam scan geometry: rows_local cut into nb ticks of R rows
    nb: int = 1
    rows: int = 0
    R: int = 0

    @property
    def num_buckets(self) -> int:
        return self.nb if self.seam else len(self.buckets)

    def describe(self) -> Tuple:
        if self.seam:
            cut: Tuple = ("scan", self.nb, self.R,
                          tuple(e.describe() for e in self.entries))
        else:
            cut = ("flat", tuple(tuple(e.describe() for e in b)
                                 for b in self.buckets))
        return (self.kind, self.pm, self.extra, self.dup, self.n,
                self.axis, self.dtype, self.gnorm_axes, cut)


class BucketPlan:
    """The full static bucketing of one engine's trainable set."""

    def __init__(self, groups: List[BucketGroup], buffer_mb: float):
        self.groups = groups
        self.buffer_mb = buffer_mb
        self._covered = {e.pid for g in groups for e in g.entries}

    def covers(self, pid: int) -> bool:
        return pid in self._covered

    def __len__(self):
        return len(self._covered)

    @property
    def num_buckets(self) -> int:
        return sum(g.num_buckets for g in self.groups)

    def describe(self) -> Tuple:
        """Canonical, picklable description — identical across ranks
        and processes for the same model/strategy (pinned by tests)."""
        return (round(self.buffer_mb, 6),
                tuple(g.describe() for g in self.groups))

    def digest(self) -> str:
        return hashlib.sha256(repr(self.describe()).encode()).hexdigest()

    def summary(self) -> Dict[str, Any]:
        """Per-bucket payload bytes (what each tick/bucket puts on the
        wire before the ring factor) — the bench line's attribution."""
        per_bucket: List[int] = []
        for g in self.groups:
            if g.seam:
                tick = sum(int(np.prod(e.shape)) for e in g.entries) \
                    // max(g.nb, 1) * _itemsize(g.dtype)
                per_bucket.extend([tick] * g.nb)
            else:
                for b in g.buckets:
                    per_bucket.append(sum(
                        int(np.prod(e.shape)) * _itemsize(e.dtype)
                        for e in b))
        return {"buckets": self.num_buckets,
                "bucket_payload_bytes": per_bucket,
                "groups": len(self.groups)}

    # -- quantized-wire support (distributed/quant_comm.py) -------------
    @staticmethod
    def _group_quantizes(g: "BucketGroup") -> bool:
        """A group quantizes when it puts a payload-sized collective on
        the wire: the ZeRO reduce-scatter ("rs") or a grad pmean.
        Groups whose only work is a dup rescale carry no residual."""
        return g.kind == "rs" or bool(g.pm)

    def residual_shapes(self) -> Dict[str, Tuple[int, ...]]:
        """LOCAL (per-rank) f32 error-feedback residual buffer shapes,
        keyed by the stable bucket name the engine checkpoints under:
        ``g<i>`` for a seam group ([nb ticks, tick payload elems] —
        the residual rides the bucket scan), ``g<i>b<j>`` for a flat
        bucket ([payload elems])."""
        out: Dict[str, Tuple[int, ...]] = {}
        for gi, g in enumerate(self.groups):
            if not self._group_quantizes(g):
                continue
            if g.seam:
                total = sum(int(np.prod(e.shape)) for e in g.entries)
                out[f"g{gi}"] = (g.nb, total // max(g.nb, 1))
            else:
                for bi, b in enumerate(g.buckets):
                    out[f"g{gi}b{bi}"] = (
                        sum(int(np.prod(e.shape)) for e in b),)
        return out

    # -- trace-time execution (inside the compiled step) ----------------
    def sync(self, grads: Dict[int, Any], qcfg=None,
             residuals: Optional[Dict[str, Any]] = None):
        """Issue every group's bucketed collectives on the raw grads.

        Returns ``(synced, gsq, new_residuals)``: the per-parameter
        synced grads (the ZeRO shard for "rs" entries — exactly what
        the unbucketed path produces), the folded global grad-norm
        sum-of-squares (f32 scalar, group psums already applied), and
        the updated per-bucket error-feedback residuals (empty unless
        ``qcfg`` quantizes and ``residuals`` carries state — keys
        match ``residual_shapes``).

        ``qcfg``: a quant_comm.QuantConfig (or None = today's
        full-precision wire, byte-for-byte untouched). When set, each
        bucket's payload quantizes to int8/fp8 + bf16 scales before
        the reduce-scatter / pmean / extra psum, and the dequantized
        local image's error feeds back through ``residuals``.
        """
        residuals = residuals or {}
        synced: Dict[int, Any] = {}
        new_res: Dict[str, Any] = {}
        gsq = jnp.float32(0.0)
        for gi, g in enumerate(self.groups):
            q = qcfg if (qcfg is not None
                         and self._group_quantizes(g)) else None
            if g.seam:
                rkey = f"g{gi}"
                resid = residuals.get(rkey) if q is not None else None
                out, sq, nr = _sync_seam_group(g, grads, qcfg=q,
                                               resid=resid, site=gi)
                if nr is not None:
                    new_res[rkey] = nr
                synced.update(out)
            else:
                sq = jnp.float32(0.0)
                for bi, bucket in enumerate(g.buckets):
                    rkey = f"g{gi}b{bi}"
                    resid = residuals.get(rkey) if q is not None \
                        else None
                    site = gi * 4096 + bi
                    if g.kind == "rs":
                        outs, bsq, nr = _sync_rs_bucket(
                            [(grads[e.pid], e.shard_dim) for e in bucket],
                            g.n, g.axis, g.pm, g.extra, qcfg=q,
                            resid=resid, site=site)
                    else:
                        outs, bsq, nr = _sync_pmean_bucket(
                            [grads[e.pid] for e in bucket],
                            [e.shape for e in bucket],
                            g.pm, g.dup, g.extra, qcfg=q,
                            resid=resid, site=site)
                    for e, o in zip(bucket, outs):
                        synced[e.pid] = o
                    if nr is not None:
                        new_res[rkey] = nr
                    sq = sq + bsq
            if g.gnorm_axes:
                from . import collective as C

                sq = C.t_psum(sq, g.gnorm_axes)
            gsq = gsq + sq
        return synced, gsq, new_res

    # -- stage-3 just-in-time param gather (the T3 mirror) ---------------
    def gather(self, shards: Dict[int, Any], qcfg=None) -> Dict[int, Any]:
        """All-gather stage-3 stored-sharded params through the same
        signature buckets the backward scatters their grads through.

        ``shards`` maps pid -> the STORED (dim-``shard_dim`` scattered)
        param value for every covered stage-3 entry; returns pid -> the
        gathered FULL value, bit-exact vs a per-parameter tiled
        ``all_gather`` on the same dim (the coalesced wire is pure data
        movement — rank-major pack, inverse unpack). Flat buckets issue
        one coalesced flat gather each (an independent dataflow node,
        so XLA's latency-hiding scheduler overlaps it with neighboring
        buckets' forward compute); seam groups run the gather as a
        ``lax.scan`` over their nb row ticks under
        ``commledger.scan_trips(nb)``, so ledger gather bytes stay
        EXACT — (p-1) x shard bytes per step, trips included.

        ``qcfg``: quant_comm's param_gather config (or None = full
        precision). Quantized, each bucket packs its flat shard once
        (int8/fp8 + bf16 scales), gathers the pair, and splices this
        rank's OWN exact shard back over its block — other ranks'
        working copies carry one quantization of noise, regenerated
        from exact shards every step; the authoritative state never
        does."""
        out: Dict[int, Any] = {}
        for g in self.groups:
            if g.kind != "rs":
                continue
            entries = [e for e in g.entries
                       if e.stored and e.pid in shards]
            if not entries:
                continue
            if g.seam:
                if len(entries) != len(g.entries):
                    continue    # engine falls back per-param
                out.update(_gather_seam_group(g, shards, qcfg=qcfg))
            else:
                for bucket in g.buckets:
                    bt = [e for e in bucket
                          if e.stored and e.pid in shards]
                    if not bt:
                        continue
                    outs = _gather_bucket(
                        [(shards[e.pid], e.shard_dim) for e in bt],
                        g.n, g.axis, qcfg=qcfg)
                    for e, o in zip(bt, outs):
                        out[e.pid] = o
        return out


# ---------------------------------------------------------------------------
# plan construction (host-side, static shapes only)
# ---------------------------------------------------------------------------


def _itemsize(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return 4


def _local_shape(shape: Sequence[int], spec, mesh) -> Tuple[int, ...]:
    """The shard shape a parameter's grad has inside shard_map."""
    out = list(int(s) for s in shape)
    for d, ax in enumerate(tuple(spec)[:len(out)]):
        if ax is None:
            continue
        for a in (ax if isinstance(ax, (tuple, list)) else (ax,)):
            if a in mesh.axis_names:
                out[d] //= int(mesh.shape[a])
    return tuple(out)


def _divisor_rows_per_tick(rows: int, row_bytes: int,
                           target: float) -> int:
    """Rows per scan tick: the divisor of ``rows`` whose chunk payload
    lands nearest the byte target (buckets must tile the row axis
    EXACTLY so ledger bytes stay closed-form — no padding)."""
    best, best_err = rows, float("inf")
    for R in range(1, rows + 1):
        if rows % R:
            continue
        err = abs(R * row_bytes - target)
        if err < best_err or (err == best_err and R < best):
            best, best_err = R, err
    return best


def build_plan(trainable: Sequence, mesh, zero, gmean_axes, data_axes,
               spec_axes_fn: Callable, grad_axes_fn: Callable,
               param_spec_fn: Callable,
               seam_row_dims: Optional[Dict[int, int]] = None,
               buffer_mb: float = DEFAULT_BUFFER_MB
               ) -> Optional[BucketPlan]:
    """Build the static bucket plan for an engine's trainable set.

    Deterministic in (parameter order, shapes/dtypes/specs, mesh axis
    sizes, the ZeRO plan, ``buffer_mb``) — identical across ranks and
    processes by construction; nothing here reads device state.
    Parameters whose grads need no collective at all (and the legacy
    local-slice ZeRO fallback) are left to the engine's unbucketed
    path. Returns None when nothing buckets.
    """
    seam_row_dims = seam_row_dims or {}
    target = max(float(buffer_mb), 1e-6) * (1 << 20)
    sigs: Dict[Tuple, BucketGroup] = {}
    order: List[Tuple] = []
    gmean_axes = tuple(gmean_axes)

    def _mesh_axes(axes) -> Tuple[str, ...]:
        return tuple(a for a in sorted(axes)
                     if a in mesh.axis_names and int(mesh.shape[a]) > 1)

    # reverse registration order ~ the tape's grad formation order
    # (backward emits last-registered layers' grads first), so bucket 0
    # is ready earliest — the T3 eager-issue ordering
    for index in range(len(trainable) - 1, -1, -1):
        p = trainable[index]
        e = zero.entry(p)
        spec_axes = frozenset(spec_axes_fn(p))
        extra = tuple(grad_axes_fn(p))
        row_dims = int(seam_row_dims.get(id(p), 0))
        lshape = _local_shape(p._value.shape, param_spec_fn(p), mesh)
        dtype = str(p._value.dtype)
        stored = False
        if e is not None and zero.axis in data_axes:
            kind = "rs"
            pm = tuple(a for a in gmean_axes if a != zero.axis)
            dup = 1
            shard_dim: Optional[int] = int(e[0])
            stored = bool(e[1])
            gnorm = _mesh_axes(spec_axes | {zero.axis})
        elif e is not None:
            continue     # legacy local-slice fallback stays unbucketed
        else:
            kind = "pmean"
            pm = tuple(a for a in gmean_axes if a not in spec_axes)
            dup = 1
            for a in gmean_axes:
                if a in spec_axes:
                    dup *= int(mesh.shape[a])
            shard_dim = None
            if not pm and not extra and dup == 1:
                continue  # nothing to sync — leave alone
            gnorm = _mesh_axes(spec_axes)
        seam = row_dims > 0
        # `stored` joins the signature so every bucket is homogeneous:
        # a bucket either gathers its params at forward entry (stage-3
        # storage) or holds replicated ones — never a mix
        key = (kind, seam, pm, extra, dup, dtype, gnorm, stored,
               row_dims if seam else 0,
               lshape[:row_dims] if seam else ())
        if key not in sigs:
            sigs[key] = BucketGroup(
                kind=kind, seam=seam, pm=pm, extra=extra, dup=dup,
                n=int(getattr(zero, "n", 1)), axis=zero.axis,
                dtype=dtype, gnorm_axes=gnorm)
            order.append(key)
        sigs[key].entries.append(BucketEntry(
            pid=id(p), index=index, shape=lshape, dtype=dtype,
            shard_dim=shard_dim, row_dims=row_dims, stored=stored))

    groups: List[BucketGroup] = []
    for key in order:
        g = sigs[key]
        if g.seam:
            rows = 1
            for d in g.entries[0].shape[:g.entries[0].row_dims]:
                rows *= int(d)
            if rows <= 0:
                continue
            row_bytes = sum(
                int(np.prod(e.shape)) * _itemsize(e.dtype)
                for e in g.entries) // rows
            g.rows = rows
            g.R = _divisor_rows_per_tick(rows, max(row_bytes, 1), target)
            g.nb = rows // g.R
        else:
            bucket: List[BucketEntry] = []
            size = 0
            for e in g.entries:
                bucket.append(e)
                size += int(np.prod(e.shape)) * _itemsize(e.dtype)
                if size >= target:
                    g.buckets.append(bucket)
                    bucket, size = [], 0
            if bucket:
                g.buckets.append(bucket)
        groups.append(g)
    if not groups:
        return None
    return BucketPlan(groups, float(buffer_mb))


# ---------------------------------------------------------------------------
# trace-time bucket sync kernels
# ---------------------------------------------------------------------------


def _shard_shape(shape: Tuple[int, ...], d: int,
                 n: int) -> Tuple[int, ...]:
    return shape[:d] + (shape[d] // n,) + shape[d + 1:]


def _rank_major(g, d: int, n: int):
    """[n, -1] view of ``g`` with rank r's scatter-dim chunk as row r,
    so a flat psum_scatter over the concatenation hands every rank
    exactly its per-parameter shards (bit-exact vs per-param rs)."""
    s = g.shape
    loc = s[d] // n
    gr = g.reshape(s[:d] + (n, loc) + s[d + 1:])
    gr = jnp.moveaxis(gr, d, 0)
    return gr.reshape(n, -1)


def _sync_rs_bucket(vals_dims, n: int, axis: str, pm, extra,
                    qcfg=None, resid=None, site: int = 0, key=None):
    """One bucket of the ZeRO path: coalesced dp-mean + extra psum +
    rank-major flat reduce-scatter. Returns (per-param shards, local
    sum-of-squares of the shard in f32, new EF residual or None).

    Quantized wire (``qcfg``): the error-feedback residual joins at
    the FIRST quantized collective in the chain (dp pmean, else the
    extra psum, else the reduce-scatter) — that is where the raw-grad
    compression error is born; downstream re-quantizations act on
    values already near the chunk lattice, so their error is second-
    order and carried statelessly (quant_comm module docstring).
    Reduction arithmetic stays f32; the synced shard casts back to the
    grad dtype at the end.
    """
    from . import collective as C

    flat = jnp.concatenate(
        [_rank_major(g, d, n) for g, d in vals_dims], axis=1).reshape(-1)
    if qcfg is None:
        if pm:
            flat = C.t_pmean(flat, pm)
        if extra:
            flat = C.t_psum(flat, extra)
        shard = C.t_psum_scatter(flat, axis, scatter_dimension=0,
                                 tiled=True) / n
        new_resid = None
        sq = jnp.sum(jnp.square(shard.astype(jnp.float32)))
    else:
        from . import quant_comm as _qc

        item = np.dtype(flat.dtype).itemsize
        if key is None:
            key = _qc.site_key(qcfg, site)

        def _k(i):
            return None if key is None else jax.random.fold_in(key, i)

        v = flat.astype(jnp.float32)
        new_resid = None
        ef_open = resid is not None
        if pm:
            if ef_open:
                v = v + resid
            out, deq = _qc.quantized_allreduce(
                v, pm, qcfg, mean=True, key=_k(0),
                logical_itemsize=item)
            if ef_open:
                new_resid = v - deq
                ef_open = False
            v = out
        if extra:
            if ef_open:
                v = v + resid
            out, deq = _qc.quantized_allreduce(
                v, extra, qcfg, mean=False, key=_k(1),
                logical_itemsize=item)
            if ef_open:
                new_resid = v - deq
                ef_open = False
            v = out
        if ef_open:
            v = v + resid
        shard32, deq = _qc.quantized_reduce_scatter(
            v, (axis,), qcfg, key=_k(2), logical_itemsize=item)
        if ef_open:
            new_resid = v - deq
        shard32 = shard32 / n
        sq = jnp.sum(jnp.square(shard32))
        shard = shard32.astype(flat.dtype)
    outs, off = [], 0
    for g, d in vals_dims:
        ss = _shard_shape(tuple(g.shape), d, n)
        m = int(np.prod(ss))
        outs.append(shard[off:off + m].reshape(ss))
        off += m
    return outs, sq, new_resid


def _sync_pmean_bucket(vals, shapes, pm, dup: int, extra,
                       qcfg=None, resid=None, site: int = 0, key=None):
    """One bucket of the replicated-grad path: coalesced pmean (+
    duplication rescale + extra psum). Returns (per-param grads, local
    sum-of-squares in f32, new EF residual or None).

    Quantized wire: the residual joins before the quantized pmean
    (EQuARX two-phase allreduce — int8 + bf16 scales both phases);
    the extra psum quantizes statelessly after."""
    from . import collective as C

    flat = jnp.concatenate([g.reshape(-1) for g in vals])
    new_resid = None
    if qcfg is None or not pm:
        if pm:
            flat = C.t_pmean(flat, pm)
        if dup > 1:
            flat = flat / dup
        if extra:
            flat = C.t_psum(flat, extra)
    else:
        from . import quant_comm as _qc

        item = np.dtype(flat.dtype).itemsize
        if key is None:
            key = _qc.site_key(qcfg, site)
        v = flat.astype(jnp.float32)
        if resid is not None:
            v = v + resid
        full, deq = _qc.quantized_allreduce(
            v, pm, qcfg, mean=True,
            key=None if key is None else jax.random.fold_in(key, 0),
            logical_itemsize=item)
        new_resid = (v - deq) if resid is not None else None
        if dup > 1:
            full = full / dup
        if extra:
            full, _ = _qc.quantized_allreduce(
                full, extra, qcfg, mean=False,
                key=None if key is None else jax.random.fold_in(key, 1),
                logical_itemsize=item)
        flat = full.astype(flat.dtype)
    outs, off = [], 0
    for s in shapes:
        m = int(np.prod(s))
        outs.append(flat[off:off + m].reshape(tuple(s)))
        off += m
    return outs, jnp.sum(jnp.square(flat.astype(jnp.float32))), new_resid


def _sync_seam_group(g: BucketGroup, grads: Dict[int, Any], qcfg=None,
                     resid=None, site: int = 0):
    """The layer-grained bucket scan over the stacked-params seam: nb
    ticks of R rows, the bucket collective issued INSIDE the tick, the
    grad-norm sum-of-squares folded into the carry. Ledger records are
    noted once with trips=nb (commledger.scan_trips) so accounting
    stays exact.

    Quantized wire (``qcfg``): the per-tick error-feedback residual
    slice rides the scan alongside the grads ([nb, tick elems] — one
    slot per tick, updated in place through the scan outputs) and the
    stochastic-rounding key (when on) folds the tick index so every
    tick rounds independently."""
    nb, R = g.nb, g.R
    xs = []
    tails: List[Tuple[int, ...]] = []
    for e in g.entries:
        arr = grads[e.pid]
        tail = tuple(arr.shape[e.row_dims:])
        tails.append(tail)
        xs.append(arr.reshape((nb, R) + tail))
    use_ef = qcfg is not None and resid is not None
    base_key = None
    if qcfg is not None:
        from . import quant_comm as _qc

        base_key = _qc.site_key(qcfg, site)
    scan_xs: Dict[str, Any] = {"g": tuple(xs)}
    if use_ef:
        scan_xs["r"] = resid
    if base_key is not None:
        scan_xs["i"] = jnp.arange(nb, dtype=jnp.uint32)

    def _tick_key(xt):
        if base_key is None:
            return None
        return jax.random.fold_in(base_key, xt["i"])

    if g.kind == "rs":
        # scatter dim in tick coords: row dims collapse to one leading
        # R axis (the ZeRO plan keeps seam entries off the row dims)
        dims = [e.shard_dim - e.row_dims + 1 for e in g.entries]

        def tick(carry, xt):
            outs, sq, nr = _sync_rs_bucket(
                list(zip(xt["g"], dims)), g.n, g.axis, g.pm, g.extra,
                qcfg=qcfg, resid=xt.get("r"), key=_tick_key(xt))
            return carry + sq, (tuple(outs), nr)
    else:
        tick_shapes = [(R,) + t for t in tails]

        def tick(carry, xt):
            outs, sq, nr = _sync_pmean_bucket(
                list(xt["g"]), tick_shapes, g.pm, g.dup, g.extra,
                qcfg=qcfg, resid=xt.get("r"), key=_tick_key(xt))
            return carry + sq, (tuple(outs), nr)

    with _cl.scan_trips(nb):
        gsq, (ys, new_resid) = lax.scan(tick, jnp.float32(0.0), scan_xs)
    synced: Dict[int, Any] = {}
    for e, y in zip(g.entries, ys):
        rows_shape = e.shape[:e.row_dims]
        out = y.reshape((nb * R,) + tuple(y.shape[2:]))
        synced[e.pid] = out.reshape(tuple(rows_shape)
                                    + tuple(y.shape[2:]))
    return synced, gsq, (new_resid if use_ef else None)


# ---------------------------------------------------------------------------
# trace-time stage-3 bucket gather kernels (the forward mirror)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def stage3_gather(flat, axis: str):
    """The stage-3 bucket all-gather: flat shard [L] -> rank-major
    [p*L] (rank r's block at r*L). A ``jax.custom_vjp`` so the
    backward exchange is the mirrored ledger-shimmed reduce-scatter
    (all_gather ↔ reduce_scatter, the vjp-ledger-symmetry pairing) —
    jax's default all_gather transpose would call raw ``lax`` and run
    outside the comm ledger."""
    from . import collective as C

    return C.t_all_gather(flat, axis, axis=0, tiled=True)


def _stage3_gather_fwd(flat, axis: str):
    return stage3_gather(flat, axis), None


def _stage3_gather_bwd(axis: str, _res, g):
    from . import collective as C

    return (C.t_psum_scatter(g, axis, scatter_dimension=0, tiled=True),)


stage3_gather.defvjp(_stage3_gather_fwd, _stage3_gather_bwd)


def _unpack_gathered(rows, vals_dims, n: int):
    """Inverse of ``_rank_major`` on a gathered ``[n, L]`` view: slice
    each param's per-rank chunks and fold the rank axis back into its
    scatter dim — bit-exact vs the per-parameter tiled all_gather
    (rank r's block lands at full[d] rows r*loc:(r+1)*loc)."""
    outs, off = [], 0
    for shard, d in vals_dims:
        s = tuple(shard.shape)
        m = int(np.prod(s))
        blk = rows[:, off:off + m].reshape((n,) + s)
        off += m
        gr = jnp.moveaxis(blk, 0, d)
        outs.append(gr.reshape(s[:d] + (s[d] * n,) + s[d + 1:]))
    return outs


def _gather_bucket(vals_dims, n: int, axis: str, qcfg=None):
    """One stage-3 bucket's just-in-time param gather: the bucket's
    stored shards coalesce into one flat buffer, all_gather once
    (``stage3_gather``), unpack per param. ``vals_dims``:
    [(stored shard value, scatter dim in the shard's coords)].

    Quantized wire (``qcfg`` = quant_comm param_gather): each param's
    flat shard packs on its OWN chunk lattice (pack_block — the exact
    per-parameter codec, so quantization values match the
    quantized_param_gather path bit-for-bit), the packed payloads and
    bf16 scale sidecars concatenate into ONE gathered pair per bucket,
    and this rank's exact flat shard splices back over its block — the
    authoritative path never sees compression noise and the bucket
    still ships as a single pair of collectives."""
    flat = jnp.concatenate([v.reshape(-1) for v, _ in vals_dims])
    L = int(flat.shape[0])
    if qcfg is None:
        rows = stage3_gather(flat, axis).reshape(n, L)
    else:
        from . import collective as C
        from . import quant_comm as _qc

        packs = [_qc.pack_block(v, qcfg) for v, _ in vals_dims]
        qcat = jnp.concatenate([q.reshape(-1) for q, _ in packs])
        scat = jnp.concatenate([s.reshape(-1) for _, s in packs])
        ratio = (int(qcat.shape[0]) * _qc.WIRE_ITEMSIZE
                 + int(scat.shape[0]) * _qc.SCALE_BYTES) \
            / float(L * np.dtype(flat.dtype).itemsize)
        qg, sg = _qc.gather_packed(qcat, scat, (axis,), ratio)

        def _deq(j):
            outs, qo, so = [], 0, 0
            for (q, s), (v, _) in zip(packs, vals_dims):
                m, nc = int(q.shape[0]), int(s.shape[0])
                outs.append(_qc.unpack_block(
                    qg[j, qo:qo + m], sg[j, so:so + nc],
                    (int(np.prod(v.shape)),), flat.dtype, qcfg))
                qo += m
                so += nc
            return jnp.concatenate(outs)

        rows = jnp.stack([_deq(j) for j in range(n)])
        idx = C.axis_index((axis,))
        rows = lax.dynamic_update_slice_in_dim(rows, flat[None], idx,
                                               axis=0)
    return _unpack_gathered(rows, vals_dims, n)


def _gather_seam_group(g: BucketGroup, shards: Dict[int, Any],
                       qcfg=None) -> Dict[int, Any]:
    """The seam group's param gather as a scan over the SAME nb ticks
    of R rows the grad sync scatters through: tick i gathers rows
    [i*R, (i+1)*R) of every stacked param's shard, so the gather rides
    the pipeline chunk seam and the ledger records carry trips=nb
    (commledger.scan_trips) — byte accounting stays exact, mirroring
    ``_sync_seam_group``."""
    nb, R = g.nb, g.R
    xs, dims = [], []
    for e in g.entries:
        arr = shards[e.pid]
        tail = tuple(arr.shape[e.row_dims:])
        # scatter dim in tick coords: row dims collapse to one leading
        # R axis (same geometry as the grad scan)
        dims.append(e.shard_dim - e.row_dims + 1)
        xs.append(arr.reshape((nb, R) + tail))

    def tick(carry, xt):
        outs = _gather_bucket(list(zip(xt, dims)), g.n, g.axis,
                              qcfg=qcfg)
        return carry, tuple(outs)

    with _cl.scan_trips(nb):
        _, ys = lax.scan(tick, jnp.float32(0.0), tuple(xs))
    full: Dict[int, Any] = {}
    for e, y in zip(g.entries, ys):
        rows_shape = e.shape[:e.row_dims]
        full[e.pid] = y.reshape(tuple(rows_shape) + tuple(y.shape[2:]))
    return full
