"""Rolling crash-consistent checkpoint manager with async saves.

Owns a base directory of per-step checkpoint dirs::

    <base>/step_00000010/   COMMIT  0.metadata  0_0.distcp  train_meta.json
    <base>/step_00000020/   ...

Each save runs the atomic commit protocol (save_state_dict.py), so the
directory invariant is: every ``step_*`` dir with a ``COMMIT`` marker is
complete and checksum-verifiable; anything else is garbage a crash left
behind (pruned on the next save). Recovery therefore never needs
coordination — :func:`latest_committed` is a pure directory scan any
relaunched process can run.

Async mode: ``save()`` snapshots device shards to host (the only stall
the train loop sees — one host copy per addressable shard at a step
boundary) and enqueues the file protocol on one background writer
thread; saves commit in submission order and ``wait()`` joins the
queue. Retention keeps the newest ``keep_last_k`` committed checkpoints
(the in-flight one excluded) so disk stays bounded on long runs.

Telemetry: the ``ckpt_*`` gauges (observability/catalog.py
``ckpt_metrics`` — schema-gated) are published after every commit and
by :meth:`publish`: last-save age / wall seconds by phase / bytes /
pending queue depth / committed step, plus a committed-saves counter.
"""
from __future__ import annotations

import json
import os
import queue
import re
import threading
import time
from typing import Any, Dict, Optional

from .load_state_dict import is_committed
from .save_state_dict import (EXTRA_META_FILE, OLD_SUFFIX, TMP_SUFFIX,
                              collect_shards, write_committed)

__all__ = ["CheckpointManager", "latest_committed", "read_extra_meta",
           "STEP_DIR_RE"]

STEP_DIR_RE = re.compile(
    r"^step_(\d+)(" + re.escape(TMP_SUFFIX) + "|"
    + re.escape(OLD_SUFFIX) + r")?$")


def latest_committed(base: str) -> Optional[str]:
    """Newest committed checkpoint directory under ``base`` (None when
    none exists). Committed ``.tmp``/``.old`` forms count — a crash
    between COMMIT and rename must not lose the save — but the final
    name wins at equal step."""
    best = None
    try:
        names = os.listdir(base)
    except OSError:
        return None
    for name in names:
        m = STEP_DIR_RE.match(name)
        if not m:
            continue
        path = os.path.join(base, name)
        if not is_committed(path):
            continue
        key = (int(m.group(1)), 0 if m.group(2) is None else -1)
        if best is None or key > best[0]:
            best = (key, path)
    return best[1] if best else None


def read_extra_meta(path: str) -> Dict[str, Any]:
    """The ``train_meta.json`` committed with a checkpoint ({} if the
    save carried none)."""
    p = os.path.join(path, EXTRA_META_FILE)
    if not os.path.isfile(p):
        return {}
    with open(p) as f:
        return json.load(f)


class CheckpointManager:
    """Rolling checkpoint directory: atomic per-step saves, keep-last-k
    retention, optional background (async) writes, ckpt_* gauges.

    >>> mgr = CheckpointManager(base, keep_last_k=3, async_save=True)
    >>> mgr.save(state, step=10, extra_meta={"step": 10})   # ~snapshot
    >>> mgr.wait()                                          # committed
    >>> latest_committed(base)
    '<base>/step_00000010'
    """

    def __init__(self, base: str, keep_last_k: int = 3,
                 async_save: bool = False, coordinator_rank: int = 0,
                 metrics_sample_s: Optional[float] = None):
        from ...observability import goodput as _gp
        from ...observability import timeseries as _ts
        from ...observability.catalog import ckpt_metrics

        self.base = base
        self.keep_last_k = max(int(keep_last_k), 1)
        self.async_save = bool(async_save)
        self.coordinator_rank = coordinator_rank
        os.makedirs(base, exist_ok=True)
        self._metrics = ckpt_metrics()
        # run-level goodput ledger lives next to the checkpoints (the
        # crash-durable journal resume_latest continues after a kill);
        # within a process the same base reuses the same live ledger
        try:
            self._goodput = _gp.attach_dir(base)
        except OSError:
            self._goodput = None     # unwritable base: saves will fail
        # optional durable metrics journal next to the goodput ledger
        # (metrics.jsonl, same flush-first crash discipline): sampled
        # every metrics_sample_s seconds when the knob is set
        self._sampler = None
        if metrics_sample_s is not None:
            try:
                self._sampler = _ts.attach_dir(
                    base, interval_s=float(metrics_sample_s))
            except (OSError, ValueError):
                self._sampler = None

        self._queue: "queue.Queue" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._cv = threading.Condition()
        self._pending = 0
        self._errors: list = []
        self._last_commit_time: Optional[float] = None
        self._last_step: Optional[int] = None

    # -- paths -----------------------------------------------------------
    def step_dir(self, step: int) -> str:
        return os.path.join(self.base, f"step_{int(step):08d}")

    def latest_committed(self) -> Optional[str]:
        return latest_committed(self.base)

    def latest_step(self) -> Optional[int]:
        p = self.latest_committed()
        if p is None:
            return None
        m = STEP_DIR_RE.match(os.path.basename(p))
        return int(m.group(1)) if m else None

    # -- saving ----------------------------------------------------------
    def save(self, state_dict: Dict, step: int,
             extra_meta: Optional[Dict[str, Any]] = None) -> None:
        """Checkpoint ``state_dict`` as ``step``. Sync mode returns
        after the commit; async mode returns after the host snapshot
        (the file protocol runs on the writer thread — ``wait()`` to
        join). A failed background save surfaces on the next call or
        ``wait()``."""
        from ...observability import goodput as _gp

        self._raise_pending()
        t0 = time.perf_counter()
        # the device->host snapshot is the only stall the step loop
        # pays in async mode — book it as ckpt_stall either way
        with _gp.segment("ckpt_stall"):
            md, shards, fname = collect_shards(state_dict)
        snap_s = time.perf_counter() - t0
        nbytes = sum(int(a.nbytes) for a in shards.values())
        job = (md, shards, fname, int(step), extra_meta, snap_s, nbytes)
        if not self.async_save:
            # sync mode: the whole commit protocol stalls the loop
            with _gp.segment("ckpt_stall"):
                self._write(*job)
            return
        if self._writer is None or not self._writer.is_alive():
            self._stop.clear()
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True, name="ckpt-writer")
            self._writer.start()
        with self._cv:
            self._pending += 1
            pending = self._pending
        self._queue.put(job)
        self._metrics["pending"].set(float(pending))

    def _writer_loop(self) -> None:
        import time as _time

        while True:
            # bounded get: a get() with no timeout can never observe
            # _stop, and close() would hang behind it forever
            try:
                job = self._queue.get(timeout=0.5)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if job is None:
                if self._stop.is_set():
                    return
                # stale wake-up sentinel from an earlier close() whose
                # writer had already exited — not a job, not a stop
                continue
            t0 = _time.time()
            try:
                self._write(*job)
            except BaseException as e:   # surfaced on wait()/next save
                with self._cv:
                    self._errors.append(e)
            finally:
                # background commit: journaled as an OVERLAPPED
                # ckpt_async interval (runs under the step loop, so it
                # is excluded from the foreground wall-sum identity)
                try:
                    if self._goodput is not None:
                        self._goodput.record_overlapped(
                            "ckpt_async", t0, _time.time())
                except Exception:
                    pass
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()

    def _write(self, md, shards, fname, step, extra_meta, snap_s,
               nbytes) -> None:
        t0 = time.perf_counter()
        write_committed(self.step_dir(step), md, shards, fname,
                        coordinator_rank=self.coordinator_rank,
                        extra_meta=extra_meta)
        write_s = time.perf_counter() - t0
        # commit bookkeeping is read by publish()/last_save_step on the
        # train-loop thread while the writer thread commits
        with self._cv:
            self._last_commit_time = time.time()
            self._last_step = step
        self._prune()
        m = self._metrics
        m["saves"].inc(result="committed")
        m["save_seconds"].set(snap_s, phase="snapshot")
        m["save_seconds"].set(write_s, phase="write")
        m["save_seconds"].set(snap_s + write_s, phase="total")
        m["save_bytes"].set(float(nbytes))
        m["last_step"].set(float(step))
        self.publish()

    def _prune(self) -> None:
        """Keep the newest ``keep_last_k`` committed checkpoints; drop
        older ones and any stale crash leftovers (uncommitted tmp/old
        dirs from steps older than the newest committed)."""
        import shutil

        entries = []
        for name in os.listdir(self.base):
            m = STEP_DIR_RE.match(name)
            if m:
                entries.append((int(m.group(1)), m.group(2) or "",
                                os.path.join(self.base, name)))
        committed = sorted((s, p) for s, suf, p in entries
                           if suf == "" and is_committed(p))
        keep = {p for _, p in committed[-self.keep_last_k:]}
        newest = committed[-1][0] if committed else -1
        for s, suf, p in entries:
            if p in keep:
                continue
            if suf == "" and is_committed(p):
                shutil.rmtree(p, ignore_errors=True)   # aged out
            elif s < newest:
                # crash leftover older than a newer committed save
                shutil.rmtree(p, ignore_errors=True)

    def _raise_pending(self) -> None:
        with self._cv:
            err = self._errors.pop(0) if self._errors else None
        if err is not None:
            raise err

    # -- synchronization / teardown -------------------------------------
    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every queued async save committed; re-raises the
        first background error."""
        with self._cv:
            self._cv.wait_for(lambda: self._pending == 0, timeout)
        self._raise_pending()

    def close(self) -> None:
        if self._writer is not None and self._writer.is_alive():
            with self._cv:
                self._cv.wait_for(lambda: self._pending == 0, 30)
            self._stop.set()
            self._queue.put(None)    # wake the bounded get immediately
            self._writer.join(timeout=30)
        self._writer = None

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- telemetry -------------------------------------------------------
    def publish(self) -> None:
        """Refresh the ckpt_last_save_age_seconds gauge (call from the
        step loop or a scrape hook; save() calls it on every commit)."""
        with self._cv:
            last_commit = self._last_commit_time
            pending = self._pending
        if last_commit is not None:
            self._metrics["age"].set(time.time() - last_commit)
        self._metrics["pending"].set(float(pending))

    @property
    def last_save_step(self) -> Optional[int]:
        with self._cv:
            return self._last_step
