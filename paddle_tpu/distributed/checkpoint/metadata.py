"""Checkpoint metadata types
(reference: python/paddle/distributed/checkpoint/metadata.py —
LocalTensorMetadata{global_offset, local_shape, dtype},
LocalTensorIndex{tensor_key, global_offset}, Metadata{state_dict_metadata,
storage_metadata, flat_mapping}).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

__all__ = ["LocalTensorMetadata", "LocalTensorIndex", "Metadata"]


@dataclass(frozen=True)
class LocalTensorMetadata:
    """One stored shard: where it sits in the global tensor."""
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str

    def to_json(self):
        return {"global_offset": list(self.global_offset),
                "local_shape": list(self.local_shape), "dtype": self.dtype}

    @staticmethod
    def from_json(d):
        return LocalTensorMetadata(tuple(d["global_offset"]),
                                   tuple(d["local_shape"]), d["dtype"])


@dataclass(frozen=True)
class LocalTensorIndex:
    tensor_key: str
    global_offset: Tuple[int, ...]

    def storage_key(self) -> str:
        off = "_".join(str(o) for o in self.global_offset)
        return f"{self.tensor_key}@{off}"


@dataclass
class Metadata:
    """state_dict_metadata: key → list of shard metadata;
    storage_metadata: storage_key → data file name;
    global_shape: key → full shape;
    checksums: storage_key → crc32 of the shard's raw bytes (computed at
    snapshot time, verified by the loader before any shard is used)."""
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = \
        field(default_factory=dict)
    storage_metadata: Dict[str, str] = field(default_factory=dict)
    global_shape: Dict[str, List[int]] = field(default_factory=dict)
    checksums: Dict[str, int] = field(default_factory=dict)

    def to_json(self):
        return {
            "state_dict_metadata": {
                k: [m.to_json() for m in v]
                for k, v in self.state_dict_metadata.items()},
            "storage_metadata": self.storage_metadata,
            "global_shape": self.global_shape,
            "checksums": self.checksums,
        }

    @staticmethod
    def from_json(d):
        md = Metadata()
        md.state_dict_metadata = {
            k: [LocalTensorMetadata.from_json(m) for m in v]
            for k, v in d["state_dict_metadata"].items()}
        md.storage_metadata = d["storage_metadata"]
        md.global_shape = d.get("global_shape", {})
        md.checksums = {k: int(v)
                        for k, v in d.get("checksums", {}).items()}
        return md
