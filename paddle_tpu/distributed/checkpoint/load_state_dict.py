"""Sharded checkpoint load with reshard-on-load.

(reference: distributed/checkpoint/load_state_dict.py — computes the
overlap between stored shards and the target distribution, point-to-point
reads the needed pieces, reassembles per rank.)

TPU-native: the stored shards are reassembled into full ndarrays and
``jax.device_put`` with each target tensor's current NamedSharding —
XLA places only the addressed shards on each device, which IS the
reshard (works across any source/target dp/mp/pp/sharding layout).
"""
from __future__ import annotations

import glob
import json
import os
import pickle
from typing import Dict

import jax
import numpy as np
import jax.numpy as jnp

from ...core.enforce import enforce
from ...tensor import Tensor
from .metadata import Metadata

__all__ = ["load_state_dict"]


def _flatten(state: Dict, prefix=""):
    out = {}
    for k, v in state.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = (state, k, v)
    return out


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    offload: bool = False) -> None:
    """Fill ``state_dict``'s tensors in place from the checkpoint at
    ``path``, resharding stored shards to each tensor's current layout."""
    meta_files = glob.glob(os.path.join(path, "*.metadata"))
    enforce(meta_files, f"no .metadata file under {path!r}")
    with open(meta_files[0]) as f:
        md = Metadata.from_json(json.load(f))

    storages = {}
    for fn in glob.glob(os.path.join(path, "*.distcp")):
        with open(fn, "rb") as f:
            storages[os.path.basename(fn)] = pickle.load(f)

    flat = _flatten(state_dict)
    for key, (owner, k, cur) in flat.items():
        if key not in md.state_dict_metadata:
            continue
        metas = md.state_dict_metadata[key]
        gshape = tuple(md.global_shape.get(
            key, metas[0].local_shape if metas else ()))
        full = np.zeros(gshape, dtype=metas[0].dtype if metas else
                        "float32")
        for m in metas:
            sk = f"{key}@" + "_".join(str(o) for o in m.global_offset)
            fname = md.storage_metadata[sk]
            data = storages[fname][sk]
            sl = tuple(slice(o, o + s) for o, s in
                       zip(m.global_offset, m.local_shape))
            full[sl] = data
        if isinstance(cur, Tensor):
            enforce(tuple(cur._value.shape) == gshape,
                    f"checkpoint tensor {key!r} has shape {gshape}, "
                    f"target expects {tuple(cur._value.shape)}")
            arr = jnp.asarray(full, dtype=cur._value.dtype)
            sharding = getattr(cur._value, "sharding", None)
            if sharding is not None and not getattr(
                    sharding, "is_fully_replicated", True):
                arr = jax.device_put(arr, sharding)  # reshard to target
            cur._value = arr
        else:
            owner[k] = full
