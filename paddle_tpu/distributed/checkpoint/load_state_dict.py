"""Sharded checkpoint load with reshard-on-load and commit verification.

(reference: distributed/checkpoint/load_state_dict.py — computes the
overlap between stored shards and the target distribution, point-to-point
reads the needed pieces, reassembles per rank.)

TPU-native: for a sharded target, ``jax.make_array_from_callback`` asks
for exactly this process's addressable shard windows; each window is
assembled from the overlapping stored shards, and storage files are
opened lazily only when one of their shards is actually needed. Host
bytes per process are therefore O(addressable shards + touched files),
not O(model) — the reshard across any source/target dp/mp/pp/sharding
layout falls out of the window/shard overlap arithmetic.

Crash consistency: the loader REFUSES a directory without the ``COMMIT``
marker the writer cuts last (a crash mid-save can never be read back),
probing ``<path>``, then a committed ``<path>.tmp`` / ``<path>.old``
(mid-rename crash windows). Every storage file is checksum-verified
against the metadata's per-shard crc32 on first open; a mismatch (or an
unparseable npz — torn write) raises :class:`CheckpointCorruptError`
instead of silently loading garbage. Newest-committed *fallback across
checkpoints* lives in ``manager.latest_committed``.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Dict, Optional

import jax
import numpy as np
import jax.numpy as jnp

from ...core.enforce import enforce
from ...tensor import Tensor
from .metadata import Metadata
from .save_state_dict import (COMMIT_MARKER, OLD_SUFFIX, TMP_SUFFIX,
                              array_crc32)

__all__ = ["load_state_dict", "is_committed", "resolve_committed",
           "CheckpointCorruptError"]


class CheckpointCorruptError(RuntimeError):
    """A committed checkpoint failed verification (checksum mismatch or
    unreadable shard archive) — fall back to an older committed one."""


def is_committed(path: str) -> bool:
    """Whether ``path`` is a fully-committed checkpoint directory (the
    writer's COMMIT marker plus a metadata file exist)."""
    return (os.path.isdir(path)
            and os.path.isfile(os.path.join(path, COMMIT_MARKER))
            and bool(glob.glob(os.path.join(path, "*.metadata"))))


def resolve_committed(path: str) -> Optional[str]:
    """The committed directory to read for ``path``: the path itself,
    else a committed ``.tmp``/``.old`` sibling left by a crash between
    the COMMIT marker and the final rename."""
    for cand in (path, path + TMP_SUFFIX, path + OLD_SUFFIX):
        if is_committed(cand):
            return cand
    return None


def _flatten(state: Dict, prefix=""):
    out = {}
    for k, v in state.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = (state, k, v)
    return out


def _as_dtype(arr: np.ndarray, dtype: str) -> np.ndarray:
    """Reinterpret an npz member with the metadata's dtype (np.savez
    round-trips ml_dtypes like bfloat16 as void records)."""
    want = np.dtype(dtype)
    if arr.dtype == want:
        return arr
    return arr.view(want) if arr.dtype.itemsize == want.itemsize \
        else arr.astype(want)


class _LazyStorages:
    """Opens .distcp files on first use (a process only pays for the
    files whose shards overlap its windows) and verifies every member's
    crc32 against the metadata before any shard is handed out."""

    def __init__(self, path: str, md: Metadata):
        self._path = path
        self._md = md
        self._cache: Dict[str, Dict] = {}

    def get(self, fname: str):
        if fname not in self._cache:
            full = os.path.join(self._path, fname)
            try:
                with np.load(full, allow_pickle=False) as z:
                    data = {k: z[k] for k in z.files}
            except Exception as e:
                raise CheckpointCorruptError(
                    f"checkpoint shard file {full!r} is unreadable "
                    f"({e}) — torn write or corruption; fall back to "
                    "an older committed checkpoint") from None
            sums = self._md.checksums
            for sk, arr in data.items():
                want = sums.get(sk)
                if want is None:
                    continue        # pre-checksum writer
                got = array_crc32(arr)
                if got != want:
                    raise CheckpointCorruptError(
                        f"checksum mismatch for shard {sk!r} in "
                        f"{full!r} (crc32 {got:#010x} != recorded "
                        f"{want:#010x}) — refusing the corrupt "
                        "checkpoint")
            self._cache[fname] = data
        return self._cache[fname]


def _window(md, storages, key, metas, gshape, dtype, sl):
    """Assemble the ``sl`` window of tensor ``key`` from the stored
    shards overlapping it."""
    shape = tuple(s.indices(d)[1] - s.indices(d)[0]
                  for s, d in zip(sl, gshape))
    out = np.zeros(shape, dtype=np.dtype(dtype))
    starts = tuple(s.indices(d)[0] for s, d in zip(sl, gshape))
    stops = tuple(s.indices(d)[1] for s, d in zip(sl, gshape))
    for m in metas:
        lo = tuple(max(o, a) for o, a in zip(m.global_offset, starts))
        hi = tuple(min(o + s, b) for o, s, b in
                   zip(m.global_offset, m.local_shape, stops))
        if any(l >= h for l, h in zip(lo, hi)):
            continue  # no overlap with this stored shard
        sk = f"{key}@" + "_".join(str(o) for o in m.global_offset)
        data = storages.get(md.storage_metadata[sk])[sk]
        data = _as_dtype(data, m.dtype).reshape(m.local_shape)
        src = tuple(slice(l - o, h - o) for l, h, o in
                    zip(lo, hi, m.global_offset))
        dst = tuple(slice(l - a, h - a) for l, h, a in zip(lo, hi, starts))
        out[dst] = data[src]
    return out


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    offload: bool = False) -> None:
    """Fill ``state_dict``'s tensors in place from the checkpoint at
    ``path``, resharding stored shards to each tensor's current layout.
    Refuses uncommitted directories; verifies shard checksums."""
    resolved = resolve_committed(path)
    enforce(resolved is not None,
            f"no committed checkpoint at {path!r}: the COMMIT marker "
            "the atomic writer cuts last is missing (crash mid-save, "
            "pre-commit-protocol directory, or wrong path). Use "
            "checkpoint.manager.latest_committed(base) to fall back to "
            "the newest committed checkpoint")
    path = resolved
    meta_files = glob.glob(os.path.join(path, "*.metadata"))
    enforce(meta_files, f"no .metadata file under {path!r}")
    with open(meta_files[0]) as f:
        md = Metadata.from_json(json.load(f))
    storages = _LazyStorages(path, md)

    flat = _flatten(state_dict)
    for key, (owner, k, cur) in flat.items():
        if key not in md.state_dict_metadata:
            continue
        metas = md.state_dict_metadata[key]
        gshape = tuple(md.global_shape.get(
            key, metas[0].local_shape if metas else ()))
        dtype = metas[0].dtype if metas else "float32"
        full_sl = tuple(slice(0, d) for d in gshape)

        if isinstance(cur, Tensor):
            enforce(tuple(cur._value.shape) == gshape,
                    f"checkpoint tensor {key!r} has shape {gshape}, "
                    f"target expects {tuple(cur._value.shape)}")
            sharding = getattr(cur._value, "sharding", None)
            if sharding is not None and not getattr(
                    sharding, "is_fully_replicated", True):
                # sharded target: assemble ONLY the addressable windows
                cur._value = jax.make_array_from_callback(
                    gshape, sharding,
                    lambda sl, key=key, metas=metas, gshape=gshape:
                    _window(md, storages, key, metas, gshape,
                            str(cur._value.dtype), sl))
            else:
                full = _window(md, storages, key, metas, gshape, dtype,
                               full_sl)
                cur._value = jnp.asarray(full, dtype=cur._value.dtype)
        else:
            owner[k] = _window(md, storages, key, metas, gshape, dtype,
                               full_sl)
