"""Sharded distributed checkpoint (paddle.distributed.checkpoint analog).

(reference: python/paddle/distributed/checkpoint/save_state_dict.py:104 —
per-rank shard files + global metadata after cross-rank dedup;
load_state_dict.py reshards on load; metadata.py LocalTensorMetadata /
LocalTensorIndex.)

Crash consistency added on top of the reference surface: atomic commit
protocol (tmp + fsync + per-shard crc32 + COMMIT marker + rename),
loader that refuses uncommitted/corrupt directories, an async save
path, and a rolling :class:`CheckpointManager` with newest-committed
fallback (`latest_committed`) — see save_state_dict.py / manager.py.
"""
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata  # noqa: F401
from .save_state_dict import (save_state_dict, wait_async_saves,  # noqa: F401
                              COMMIT_MARKER, array_crc32)
from .load_state_dict import (load_state_dict, is_committed,  # noqa: F401
                              resolve_committed, CheckpointCorruptError)
from .manager import (CheckpointManager, latest_committed,  # noqa: F401
                      read_extra_meta)

__all__ = ["save_state_dict", "load_state_dict", "Metadata",
           "LocalTensorMetadata", "LocalTensorIndex", "CheckpointManager",
           "latest_committed", "read_extra_meta", "is_committed",
           "resolve_committed", "CheckpointCorruptError",
           "wait_async_saves", "COMMIT_MARKER", "array_crc32"]
