"""Sharded distributed checkpoint (paddle.distributed.checkpoint analog).

(reference: python/paddle/distributed/checkpoint/save_state_dict.py:104 —
per-rank shard files + global metadata after cross-rank dedup;
load_state_dict.py reshards on load; metadata.py LocalTensorMetadata /
LocalTensorIndex.)
"""
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata  # noqa: F401
from .save_state_dict import save_state_dict  # noqa: F401
from .load_state_dict import load_state_dict  # noqa: F401

__all__ = ["save_state_dict", "load_state_dict", "Metadata",
           "LocalTensorMetadata", "LocalTensorIndex"]
