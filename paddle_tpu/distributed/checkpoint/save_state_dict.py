"""Sharded checkpoint save with a crash-consistent commit protocol.

(reference: distributed/checkpoint/save_state_dict.py:50-104 — each rank
writes its local shards to `<rank>_0.distcp` after a cross-rank dedup
pass, rank 0 writes `<n>.metadata`.)

TPU-native: tensors are global ``jax.Array``s whose addressable shards
already describe the physical layout, so "dedup" is structural — each
unique (tensor, global_offset) shard is written once, replicated copies
are skipped. Process index 0 of a multi-host job writes only its
addressable shards plus the metadata; other hosts write theirs.

Crash consistency (the commit protocol):

1. every file is written into ``<path>.tmp`` and fsync'd;
2. shard files are ``np.savez`` archives (no arbitrary-code-execution
   on load of an untrusted checkpoint, unlike pickle) with a crc32 per
   shard recorded in the metadata;
3. ``0.metadata`` is written only after every shard file is durable;
4. a ``COMMIT`` marker is written last, the directory fsync'd, and the
   whole tmp directory atomically renamed to ``<path>``.

A crash at ANY point leaves either the previous committed checkpoint
untouched or a tmp/old directory the loader refuses (no COMMIT) or
falls back from (committed ``.tmp``/``.old`` after a mid-rename crash).
The write path carries the ``ckpt.write_shard`` / ``ckpt.write_metadata``
/ ``ckpt.commit`` / ``ckpt.rename`` failpoints
(distributed/failpoints.py) so the crash-consistency property is
actually tested, not assumed.

``async_save=True`` snapshots the device shards to host (the only
blocking part) and performs the file protocol on a background writer
thread; ``wait_async_saves()`` blocks until pending writes commit.
The rolling-retention form of this lives in
:class:`~paddle_tpu.distributed.checkpoint.manager.CheckpointManager`.
"""
from __future__ import annotations

import io
import json
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...tensor import Tensor
from .. import failpoints as _fp
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata

__all__ = ["save_state_dict", "wait_async_saves", "collect_shards",
           "write_committed", "array_crc32", "COMMIT_MARKER",
           "TMP_SUFFIX", "OLD_SUFFIX", "EXTRA_META_FILE"]

COMMIT_MARKER = "COMMIT"
TMP_SUFFIX = ".tmp"
OLD_SUFFIX = ".old"
EXTRA_META_FILE = "train_meta.json"
_FORMAT_VERSION = 1


def _flatten(state: Dict, prefix=""):
    out = {}
    for k, v in state.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def array_crc32(arr) -> int:
    """The shard checksum codec: crc32 over the C-contiguous byte
    image of one array. Shared by the checkpoint writer/loader and the
    serving KV page-migration wire format (inference/disagg.py), so a
    page payload is checked exactly like a checkpoint shard."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _slices_to_offset(index, shape):
    off = []
    for d, sl in enumerate(index):
        start = sl.start if isinstance(sl, slice) and sl.start else 0
        off.append(int(start))
    while len(off) < len(shape):
        off.append(0)
    return tuple(off)


# ---------------------------------------------------------------------------
# snapshot: device shards -> host arrays + metadata (the blocking part)
# ---------------------------------------------------------------------------


def collect_shards(state_dict: Dict) -> Tuple[Metadata, Dict[str,
                                                             np.ndarray],
                                              str]:
    """Host-side snapshot of a state dict: metadata + the per-shard
    numpy arrays this process will write, with crc32 checksums.

    This is the only part of a save that touches the device (one
    host copy per addressable shard) — everything after it is pure file
    I/O, which is what the async path runs on a background thread.
    """
    proc = jax.process_index()
    flat = _flatten(state_dict)

    md = Metadata()
    shards_out: Dict[str, np.ndarray] = {}
    fname = f"{proc}_0.distcp"
    for key, v in flat.items():
        if isinstance(v, Tensor):
            v = v._value
        if not isinstance(v, jax.Array):
            v = np.asarray(v)
            md.state_dict_metadata[key] = [LocalTensorMetadata(
                (0,) * v.ndim, tuple(v.shape), str(v.dtype))]
            idx = LocalTensorIndex(key, (0,) * v.ndim)
            sk = idx.storage_key()
            md.storage_metadata[sk] = fname
            md.global_shape[key] = list(v.shape)
            md.checksums[sk] = array_crc32(v)
            shards_out[sk] = v
            continue
        md.global_shape[key] = list(v.shape)
        metas, seen = [], set()
        for sh in v.addressable_shards:
            off = _slices_to_offset(sh.index, v.shape)
            if off in seen:  # replicated copy — dedup
                continue
            seen.add(off)
            data = np.asarray(sh.data)
            metas.append(LocalTensorMetadata(off, tuple(data.shape),
                                             str(data.dtype)))
            idx = LocalTensorIndex(key, off)
            sk = idx.storage_key()
            md.storage_metadata[sk] = fname
            md.checksums[sk] = array_crc32(data)
            shards_out[sk] = data
        md.state_dict_metadata[key] = metas
    return md, shards_out, fname


# ---------------------------------------------------------------------------
# durable file helpers
# ---------------------------------------------------------------------------


def _write_durable(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:        # platforms without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _rmtree(path: str) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)


def _replace_dir(tmp: str, final: str) -> None:
    """Atomically promote ``tmp`` to ``final``. A pre-existing committed
    ``final`` is renamed aside first (loaders probe ``<final>.old`` /
    ``<final>.tmp`` as fallbacks, so no crash window is uncovered)."""
    bak = final + OLD_SUFFIX
    if os.path.isdir(final):
        _rmtree(bak)
        os.rename(final, bak)
    os.rename(tmp, final)
    _rmtree(bak)
    _fsync_dir(os.path.dirname(os.path.abspath(final)))


# ---------------------------------------------------------------------------
# the commit protocol (pure file I/O over a collected snapshot)
# ---------------------------------------------------------------------------


def write_committed(path: str, md: Metadata,
                    shards: Dict[str, np.ndarray], fname: str,
                    coordinator_rank: int = 0,
                    extra_meta: Optional[Dict[str, Any]] = None) -> None:
    """Run the tmp → fsync → metadata → COMMIT → rename protocol for a
    collected snapshot. Multi-host: every process writes its shard file,
    the coordinator merges metadata and performs the commit."""
    from .. import runtime as _rt

    proc = jax.process_index()
    tmp = path.rstrip("/") + TMP_SUFFIX
    os.makedirs(tmp, exist_ok=True)

    bio = io.BytesIO()
    np.savez(bio, **shards)
    data = _fp.hit("ckpt.write_shard", bio.getvalue())
    _write_durable(os.path.join(tmp, fname), data)

    # Multi-host: the coordinator's own addressable shards are only a
    # slice of the global layout — gather every process's local metadata
    # before writing 0.metadata, or load_state_dict would silently
    # zero-fill the missing regions (reference save_state_dict.py:50-104
    # does the same all_gather_object pass before rank 0 writes).
    if _rt.is_multiprocess():
        all_md = _rt.all_gather_object_host(
            (md.state_dict_metadata, md.storage_metadata, md.global_shape,
             md.checksums))
        if proc == coordinator_rank:
            merged = Metadata()
            for sd_md, st_md, gshape, sums in all_md:
                for key, metas in sd_md.items():
                    have = merged.state_dict_metadata.setdefault(key, [])
                    seen_off = {tuple(m.global_offset) for m in have}
                    for m in metas:
                        if tuple(m.global_offset) not in seen_off:
                            have.append(m)
                            seen_off.add(tuple(m.global_offset))
                merged.storage_metadata.update(st_md)
                merged.global_shape.update(gshape)
                merged.checksums.update(sums)
            md = merged
        # every shard file must be durable before the commit is cut
        _rt.host_barrier("ckpt_shards")
    if proc == coordinator_rank:
        meta_bytes = _fp.hit("ckpt.write_metadata",
                             json.dumps(md.to_json()).encode())
        _write_durable(os.path.join(tmp, "0.metadata"), meta_bytes)
        if extra_meta is not None:
            _write_durable(os.path.join(tmp, EXTRA_META_FILE),
                           json.dumps(extra_meta).encode())
        _fp.hit("ckpt.commit")
        commit = {"format": _FORMAT_VERSION,
                  "shard_files": sorted({v for v in
                                         md.storage_metadata.values()}),
                  "n_tensors": len(md.state_dict_metadata)}
        _write_durable(os.path.join(tmp, COMMIT_MARKER),
                       json.dumps(commit).encode())
        _fsync_dir(tmp)
        _fp.hit("ckpt.rename")
        _replace_dir(tmp, path)
    if _rt.is_multiprocess():
        _rt.host_barrier("ckpt_save")  # all files durable before return


# ---------------------------------------------------------------------------
# public save entry point (+ the module-level async writer)
# ---------------------------------------------------------------------------

_async_lock = threading.Lock()
_async_threads: List[threading.Thread] = []
_async_errors: List[BaseException] = []


def _drain_finished() -> None:
    with _async_lock:
        _async_threads[:] = [t for t in _async_threads if t.is_alive()]


def wait_async_saves(timeout: Optional[float] = None) -> None:
    """Block until every ``save_state_dict(async_save=True)`` issued by
    this process has committed; re-raises the first background error."""
    with _async_lock:
        threads = list(_async_threads)
    for t in threads:
        t.join(timeout)
    _drain_finished()
    with _async_lock:
        if _async_errors:
            raise _async_errors.pop(0)


def save_state_dict(state_dict: Dict, path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False,
                    extra_meta: Optional[Dict[str, Any]] = None) -> None:
    """Write a sharded checkpoint under ``path`` (a directory), with
    the atomic commit protocol.

    Layout: ``<proc>_0.distcp`` (npz of shards) + ``0.metadata`` (json,
    incl. per-shard crc32) + ``COMMIT`` (marker, written last).

    ``async_save``: snapshot to host now (the only stall), run the file
    protocol on a background thread (``wait_async_saves()`` joins it).
    ``extra_meta``: small json dict committed atomically WITH the shards
    as ``train_meta.json`` (step counters, RNG, scaler state — anything
    that must never be newer or older than the tensors next to it).
    """
    from ...core.enforce import enforce

    enforce(unique_id is None,
            "save_state_dict(unique_id=...) is not implemented: the "
            "atomic commit protocol identifies a save by its directory "
            "(use CheckpointManager for per-step rolling names)")
    md, shards, fname = collect_shards(state_dict)
    if not async_save:
        write_committed(path, md, shards, fname, coordinator_rank,
                        extra_meta)
        return

    def _bg():
        try:
            write_committed(path, md, shards, fname, coordinator_rank,
                            extra_meta)
        except BaseException as e:       # surfaced by wait_async_saves
            with _async_lock:
                _async_errors.append(e)

    t = threading.Thread(target=_bg, daemon=True, name="ckpt-writer")
    with _async_lock:
        _async_threads.append(t)
    t.start()
    _drain_finished()
