"""Sharded checkpoint save.

(reference: distributed/checkpoint/save_state_dict.py:50-104 — each rank
writes its local shards to `<rank>_0.distcp` after a cross-rank dedup
pass, rank 0 writes `<n>.metadata`.)

TPU-native: tensors are global ``jax.Array``s whose addressable shards
already describe the physical layout, so "dedup" is structural — each
unique (tensor, global_offset) shard is written once, replicated copies
are skipped. Process index 0 of a multi-host job writes only its
addressable shards plus the metadata; other hosts write theirs.
"""
from __future__ import annotations

import json
import os
from typing import Dict

import jax
import numpy as np

from ...tensor import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata

__all__ = ["save_state_dict"]


def _flatten(state: Dict, prefix=""):
    out = {}
    for k, v in state.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _slices_to_offset(index, shape):
    off = []
    for d, sl in enumerate(index):
        start = sl.start if isinstance(sl, slice) and sl.start else 0
        off.append(int(start))
    while len(off) < len(shape):
        off.append(0)
    return tuple(off)


def save_state_dict(state_dict: Dict, path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False) -> None:
    """Write a sharded checkpoint under ``path`` (a directory).

    Layout: ``<proc>_0.distcp`` (npz of shards) + ``0.metadata`` (json).
    """
    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    flat = _flatten(state_dict)

    md = Metadata()
    shards_out = {}
    fname = f"{proc}_0.distcp"
    for key, v in flat.items():
        if isinstance(v, Tensor):
            v = v._value
        if not isinstance(v, jax.Array):
            v = np.asarray(v)
            md.state_dict_metadata[key] = [LocalTensorMetadata(
                (0,) * v.ndim, tuple(v.shape), str(v.dtype))]
            idx = LocalTensorIndex(key, (0,) * v.ndim)
            md.storage_metadata[idx.storage_key()] = fname
            md.global_shape[key] = list(v.shape)
            shards_out[idx.storage_key()] = v
            continue
        md.global_shape[key] = list(v.shape)
        metas, seen = [], set()
        for sh in v.addressable_shards:
            off = _slices_to_offset(sh.index, v.shape)
            if off in seen:  # replicated copy — dedup
                continue
            seen.add(off)
            data = np.asarray(sh.data)
            metas.append(LocalTensorMetadata(off, tuple(data.shape),
                                             str(data.dtype)))
            idx = LocalTensorIndex(key, off)
            md.storage_metadata[idx.storage_key()] = fname
            shards_out[idx.storage_key()] = data
        md.state_dict_metadata[key] = metas

    import pickle

    with open(os.path.join(path, fname), "wb") as f:
        pickle.dump(shards_out, f, protocol=4)

    # Multi-host: the coordinator's own addressable shards are only a
    # slice of the global layout — gather every process's local metadata
    # before writing 0.metadata, or load_state_dict would silently
    # zero-fill the missing regions (reference save_state_dict.py:50-104
    # does the same all_gather_object pass before rank 0 writes).
    from .. import runtime as _rt

    if _rt.is_multiprocess():
        all_md = _rt.all_gather_object_host(
            (md.state_dict_metadata, md.storage_metadata, md.global_shape))
        if proc == coordinator_rank:
            merged = Metadata()
            for sd_md, st_md, gshape in all_md:
                for key, metas in sd_md.items():
                    have = merged.state_dict_metadata.setdefault(key, [])
                    seen_off = {tuple(m.global_offset) for m in have}
                    for m in metas:
                        if tuple(m.global_offset) not in seen_off:
                            have.append(m)
                            seen_off.add(tuple(m.global_offset))
                merged.storage_metadata.update(st_md)
                merged.global_shape.update(gshape)
            md = merged
    if proc == coordinator_rank:
        with open(os.path.join(path, "0.metadata"), "w") as f:
            json.dump(md.to_json(), f)
    if _rt.is_multiprocess():
        _rt.host_barrier("ckpt_save")  # all files durable before return
