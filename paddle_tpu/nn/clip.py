"""Gradient clipping (reference: python/paddle/nn/clip.py).

ClipGradByGlobalNorm matches the reference semantics (global norm across
all grads, scale if above max). The actual arithmetic runs inside the
optimizer's fused jitted update when possible.
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

__all__ = ["ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"]


class ClipGradBase:
    def apply_values(self, grads: List):
        """Operate on raw jax arrays (called inside jitted update)."""
        raise NotImplementedError


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm: float, group_name: str = "default_group",
                 auto_skip_clip: bool = False):
        self.clip_norm = float(clip_norm)

    def apply_values(self, grads, extra_sq=0.0):
        """extra_sq: squared-norm contribution of gradients clipped
        elsewhere under the SAME global norm (the optimizer's merged
        SelectedRows grads — reference: ClipGradByGlobalNorm merges
        sparse grads into the global norm before scaling)."""
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        sq = sq + extra_sq
        global_norm = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-6),
                            1.0)
        return [(g * scale).astype(g.dtype) for g in grads], global_norm

    def coefficient(self, global_norm):
        """Scale factor for a given global norm (shared with the sparse
        path so both sides clip by the identical coefficient)."""
        return jnp.minimum(
            self.clip_norm / jnp.maximum(global_norm, 1e-6), 1.0)

    def __repr__(self):
        return f"ClipGradByGlobalNorm(clip_norm={self.clip_norm})"


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm: float):
        self.clip_norm = float(clip_norm)

    def apply_values(self, grads):
        out = []
        for g in grads:
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-6), 1.0)
            out.append((g * scale).astype(g.dtype))
        return out, None


class ClipGradByValue(ClipGradBase):
    def __init__(self, max: float, min: float = None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply_values(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads], None
