"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py —
SimpleRNNCell:697, LSTMCell:876, GRUCell:1074, RNN:1268, RNNBase:1426,
SimpleRNN/LSTM/GRU:1724/1846/1972 over the phi rnn kernel, which
dynloads cuDNN RNN descriptors on GPU).

TPU design: each (layer, direction) pass is ONE ``lax.scan`` over time
— compiled once for any length, differentiable through the scan, no
per-step dispatch. The input-to-hidden projection for ALL timesteps is
hoisted out of the scan as a single [T*B, in] x [in, gates*h] matmul
(MXU-shaped), so the recurrence only carries the [B, h] state GEMMs.
Gate order matches the reference (LSTM: i,f,g,o; GRU: r,z,c), which is
also cuDNN/torch order — state dicts port over directly.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op
from ..core.enforce import enforce
from ..tensor import Tensor
from .container import LayerList
from .layer import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU", "BiRNN"]


def _act(name):
    return jnp.tanh if name == "tanh" else (lambda x: jnp.maximum(x, 0))


# ---------------------------------------------------------------------------
# scan kernels: x is TIME-MAJOR [T, B, in] inside the kernel
# ---------------------------------------------------------------------------
def _order(x, lens, reverse):
    """Per-row time order: reversed rows flip only their VALID prefix
    (padded steps stay in place), so both directions share the same
    freeze-past-length recurrence."""
    T = x.shape[0]
    if not reverse:
        return x
    if lens is None:
        return x[::-1]
    t = jnp.arange(T)[:, None]                      # [T, 1]
    idx = jnp.where(t < lens[None, :], lens[None, :] - 1 - t, t)  # [T, B]
    return jnp.take_along_axis(x, idx[:, :, None], axis=0)


def _live_mask(lens, T):
    if lens is None:
        return None
    return jnp.arange(T)[:, None] < lens[None, :]    # [T, B]


@def_op("rnn_scan")
def _rnn_scan(x, h0, w_ih, w_hh, b_ih, b_hh, reverse, activation,
              lens=None):
    act = _act(activation)
    T = x.shape[0]
    xt = _order(x, lens, reverse)
    i2h = xt @ w_ih.T + (b_ih if b_ih is not None else 0.0)
    live = _live_mask(lens, T)

    def step(h, inp):
        i2h_t, live_t = inp
        hn = act(i2h_t + h @ w_hh.T + (b_hh if b_hh is not None else 0.0))
        if live_t is not None:
            hn = jnp.where(live_t[:, None], hn, h)
            out = jnp.where(live_t[:, None], hn, jnp.zeros_like(hn))
        else:
            out = hn
        return hn, out

    hN, ys = lax.scan(step, h0, (i2h, live))
    return _order(ys, lens, reverse), hN


@def_op("lstm_scan")
def _lstm_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse, lens=None):
    T = x.shape[0]
    xt = _order(x, lens, reverse)
    i2h = xt @ w_ih.T + (b_ih if b_ih is not None else 0.0)
    H = h0.shape[-1]
    live = _live_mask(lens, T)

    def step(carry, inp):
        h, c = carry
        i2h_t, live_t = inp
        g = i2h_t + h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        i = jax.nn.sigmoid(g[:, 0 * H:1 * H])
        f = jax.nn.sigmoid(g[:, 1 * H:2 * H])
        gg = jnp.tanh(g[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(g[:, 3 * H:4 * H])
        cn = f * c + i * gg
        hn = o * jnp.tanh(cn)
        if live_t is not None:
            hn = jnp.where(live_t[:, None], hn, h)
            cn = jnp.where(live_t[:, None], cn, c)
            out = jnp.where(live_t[:, None], hn, jnp.zeros_like(hn))
        else:
            out = hn
        return (hn, cn), out

    (hN, cN), ys = lax.scan(step, (h0, c0), (i2h, live))
    return _order(ys, lens, reverse), hN, cN


@def_op("gru_scan")
def _gru_scan(x, h0, w_ih, w_hh, b_ih, b_hh, reverse, lens=None):
    T = x.shape[0]
    xt = _order(x, lens, reverse)
    i2h = xt @ w_ih.T + (b_ih if b_ih is not None else 0.0)
    H = h0.shape[-1]
    live = _live_mask(lens, T)

    def step(h, inp):
        i2h_t, live_t = inp
        hg = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        r = jax.nn.sigmoid(i2h_t[:, :H] + hg[:, :H])
        z = jax.nn.sigmoid(i2h_t[:, H:2 * H] + hg[:, H:2 * H])
        c = jnp.tanh(i2h_t[:, 2 * H:] + r * hg[:, 2 * H:])
        hn = (h - c) * z + c         # == z*h + (1-z)*c (reference form)
        if live_t is not None:
            hn = jnp.where(live_t[:, None], hn, h)
            out = jnp.where(live_t[:, None], hn, jnp.zeros_like(hn))
        else:
            out = hn
        return hn, out

    hN, ys = lax.scan(step, h0, (i2h, live))
    return _order(ys, lens, reverse), hN


# ---------------------------------------------------------------------------
# cells (single-step API, reference rnn.py:697/876/1074)
# ---------------------------------------------------------------------------
class RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gates,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        from .initializer import Uniform

        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (gates * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (gates * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=init)
        # attr=False -> no bias (the scan kernels handle None)
        self.bias_ih = None if bias_ih_attr is False else \
            self.create_parameter((gates * hidden_size,), is_bias=True,
                                  attr=bias_ih_attr,
                                  default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else \
            self.create_parameter((gates * hidden_size,), is_bias=True,
                                  attr=bias_hh_attr,
                                  default_initializer=init)

    def _zeros(self, inputs, n):
        B = inputs.shape[0]
        z = jnp.zeros((B, self.hidden_size), inputs._value.dtype)
        if n == 1:
            return Tensor(z, stop_gradient=True)
        return tuple(Tensor(z, stop_gradient=True) for _ in range(n))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 1, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
        enforce(activation in ("tanh", "relu"),
                lambda: f"activation must be tanh/relu, got {activation}")
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = self._zeros(inputs, 1)
        ys, _ = _rnn_scan(_expand_t(inputs), states, self.weight_ih,
                          self.weight_hh, self.bias_ih, self.bias_hh,
                          False, self.activation)
        h = _squeeze_t(ys)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 4, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self._zeros(inputs, 2)
        h0, c0 = states
        ys, hN, cN = _lstm_scan(_expand_t(inputs), h0, c0, self.weight_ih,
                                self.weight_hh, self.bias_ih,
                                self.bias_hh, False)
        h = _squeeze_t(ys)
        return h, (h, cN)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 3, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self._zeros(inputs, 1)
        ys, hN = _gru_scan(_expand_t(inputs), states, self.weight_ih,
                           self.weight_hh, self.bias_ih, self.bias_hh,
                           False)
        h = _squeeze_t(ys)
        return h, h


def _expand_t(x):
    """[B, in] -> [1, B, in] for the scan kernels."""
    from ..ops.manipulation import unsqueeze

    return unsqueeze(x, 0)


def _squeeze_t(x):
    from ..ops.manipulation import squeeze

    return squeeze(x, 0)


# ---------------------------------------------------------------------------
# sequence runners
# ---------------------------------------------------------------------------
class RNN(Layer):
    """Run a cell over a sequence (reference rnn.py:1268). The whole
    sequence runs in the cell's scan kernel when the cell is one of the
    builtin cells; custom cells fall back to a python loop over time
    (traceable under jit.to_static)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        lens = sequence_length
        # exact-type checks: a SUBCLASS with an overridden forward must
        # take the custom-cell path, not the parent's fused equations
        if type(self.cell) is LSTMCell:
            if initial_states is None:
                initial_states = self.cell._zeros(x[0], 2)
            h0, c0 = initial_states
            ys, hN, cN = _lstm_scan(x, h0, c0, self.cell.weight_ih,
                                    self.cell.weight_hh,
                                    self.cell.bias_ih, self.cell.bias_hh,
                                    self.is_reverse, lens=lens)
            out = ys if self.time_major else ys.transpose([1, 0, 2])
            return out, (hN, cN)
        if type(self.cell) is GRUCell:
            if initial_states is None:
                initial_states = self.cell._zeros(x[0], 1)
            ys, hN = _gru_scan(x, initial_states, self.cell.weight_ih,
                               self.cell.weight_hh, self.cell.bias_ih,
                               self.cell.bias_hh, self.is_reverse,
                               lens=lens)
            return (ys if self.time_major
                    else ys.transpose([1, 0, 2])), hN
        if type(self.cell) is SimpleRNNCell:
            if initial_states is None:
                initial_states = self.cell._zeros(x[0], 1)
            ys, hN = _rnn_scan(x, initial_states, self.cell.weight_ih,
                               self.cell.weight_hh, self.cell.bias_ih,
                               self.cell.bias_hh, self.is_reverse,
                               self.cell.activation, lens=lens)
            return (ys if self.time_major
                    else ys.transpose([1, 0, 2])), hN
        # custom cell: python time loop
        enforce(lens is None,
                "sequence_length with a custom cell is not supported; "
                "mask outputs manually")
        T = x.shape[0]
        states = initial_states
        outs = []
        ts = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in ts:
            y, states = self.cell(x[t], states)
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        from ..ops.manipulation import stack

        ys = stack(outs, axis=0)
        return (ys if self.time_major else ys.transpose([1, 0, 2])), states


class BiRNN(Layer):
    """Forward + backward cells over one sequence (reference
    rnn.py:1352)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        s_fw = s_bw = None
        if initial_states is not None:
            s_fw, s_bw = initial_states
        y_fw, st_fw = self.rnn_fw(inputs, s_fw,
                                  sequence_length=sequence_length)
        y_bw, st_bw = self.rnn_bw(inputs, s_bw,
                                  sequence_length=sequence_length)
        from ..ops.manipulation import concat

        return concat([y_fw, y_bw], axis=-1), (st_fw, st_bw)


class RNNBase(LayerList):
    """Stacked multi-layer (optionally bidirectional) recurrence
    (reference rnn.py:1426)."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, activation="tanh"):
        super().__init__()
        enforce(direction in ("forward", "bidirect", "bidirectional"),
                lambda: f"unknown direction {direction!r}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.num_directions = 1 if direction == "forward" else 2
        self.state_components = 2 if mode == "LSTM" else 1

        def make_cell(in_sz):
            if mode == "LSTM":
                return LSTMCell(in_sz, hidden_size)
            if mode == "GRU":
                return GRUCell(in_sz, hidden_size)
            return SimpleRNNCell(in_sz, hidden_size, activation)

        for i in range(num_layers):
            in_sz = input_size if i == 0 \
                else hidden_size * self.num_directions
            if self.num_directions == 1:
                self.append(RNN(make_cell(in_sz), False, time_major))
            else:
                self.append(BiRNN(make_cell(in_sz), make_cell(in_sz),
                                  time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import stack
        from .functional import dropout as _dropout

        B_axis = 1 if self.time_major else 0
        L, D = self.num_layers, self.num_directions
        states_in = None
        if initial_states is not None:
            # [L*D, B, H] (or a (h, c) tuple of those for LSTM)
            if self.state_components == 2:
                h_all, c_all = initial_states
                states_in = [(h_all[i], c_all[i]) for i in range(L * D)]
            else:
                states_in = [initial_states[i] for i in range(L * D)]

        x = inputs
        h_outs, c_outs = [], []
        for li, layer in enumerate(self):
            if states_in is None:
                st = None
            elif D == 1:
                st = states_in[li]
            else:
                st = (states_in[2 * li], states_in[2 * li + 1])
            x, st_out = layer(x, st, sequence_length=sequence_length)
            if D == 1:
                st_list = [st_out]
            else:
                st_list = list(st_out)
            for s in st_list:
                if self.state_components == 2:
                    h_outs.append(s[0])
                    c_outs.append(s[1])
                else:
                    h_outs.append(s)
            if self.dropout and li < len(self._sub_layers) - 1:
                x = _dropout(x, p=self.dropout, training=self.training)
        if self.state_components == 2:
            return x, (stack(h_outs, axis=0), stack(c_outs, axis=0))
        return x, stack(h_outs, axis=0)


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout,
                         activation=activation)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)
