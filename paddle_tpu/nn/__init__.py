"""paddle_tpu.nn — layers + functional (paddle.nn analog)."""
from . import functional  # noqa: F401
from . import quant  # noqa: F401
from . import initializer  # noqa: F401
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .container import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .layer import Layer  # noqa: F401
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .extra_layers import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
