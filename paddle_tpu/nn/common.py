"""Common layers: Linear, Embedding, Dropout, etc.

(reference: python/paddle/nn/layer/common.py)
"""
from __future__ import annotations

from typing import Optional

from ..framework.param_attr import ParamAttr
from ..tensor import Parameter
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = ["Linear", "Embedding", "Dropout", "Dropout2D", "Flatten",
           "Identity", "Upsample", "UpsamplingBilinear2D", "PixelShuffle"]


class Linear(Layer):
    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr))
        self.bias = self.create_parameter(
            (out_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (padding_idx if padding_idx is None or padding_idx >= 0
                             else num_embeddings + padding_idx)
        self._sparse = bool(sparse)
        attr = ParamAttr._to_attr(weight_attr)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=attr,
            default_initializer=None if (attr and attr.initializer) else I.Normal(0.0, 1.0))
        if self._padding_idx is not None:
            self.weight._value = self.weight._value.at[self._padding_idx].set(0.0)

    def forward(self, x):
        if self._sparse:
            from .sparse_embedding import sparse_embedding

            return sparse_embedding(x, self.weight, self._padding_idx)
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p: float = 0.5, axis=None, mode: str = "upscale_in_train",
                 name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Dropout):
    pass


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten

        return flatten(x, start_axis=self.start_axis, stop_axis=self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=tuple(self.size) if self.size else None,
                             scale_factor=self.scale_factor, mode=self.mode,
                             align_corners=self.align_corners,
                             data_format=self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor: int, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, upscale_factor=self.upscale_factor)
