"""Long-tail layer classes (reference: python/paddle/nn/layer/* rows
previously absent here — 1-D/3-D pooling and convs, padding layers,
distance/similarity, the loss-zoo tail, unpool/fold wrappers,
SpectralNorm). Thin compositions over the op registry; each docstring
names its reference class.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op
from ..core.enforce import enforce
from ..ops import extra as _extra
from ..tensor import Tensor
from . import functional as F
from .layer import Layer

__all__ = [
    "AvgPool1D", "MaxPool1D", "AvgPool3D", "MaxPool3D",
    "AdaptiveAvgPool1D", "AdaptiveMaxPool1D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool3D", "MaxUnPool1D", "MaxUnPool2D",
    "Conv3D", "Conv1DTranspose", "Conv3DTranspose",
    "Dropout3D", "AlphaDropout",
    "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
    "Bilinear", "CosineSimilarity", "PairwiseDistance",
    "LogSigmoid", "Maxout", "RReLU", "ThresholdedReLU", "Softmax2D",
    "ChannelShuffle", "PixelUnshuffle", "Fold", "Unfold", "Unflatten",
    "UpsamplingNearest2D", "SpectralNorm",
    "InstanceNorm1D", "InstanceNorm3D",
    "CTCLoss", "HuberLoss", "CosineEmbeddingLoss", "GaussianNLLLoss",
    "HingeEmbeddingLoss", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
    "PoissonNLLLoss", "SoftMarginLoss", "TripletMarginLoss",
    "TripletMarginWithDistanceLoss", "HSigmoidLoss",
]


def _pair(v, n=2):
    return (int(v),) * n if np.isscalar(v) else tuple(int(i) for i in v)


def _reduce(loss, reduction):
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


# ---------------------------------------------------------------------------
# 1-D / 3-D pooling (reference: nn/layer/pooling.py)
# ---------------------------------------------------------------------------
class _Pool1D(Layer):
    def __init__(self, kernel_size, stride, padding, mode,
                 ceil_mode=False):
        super().__init__()
        enforce(not ceil_mode, "ceil_mode is not supported here")
        self.k = kernel_size
        self.s = stride or kernel_size
        self.p = padding
        self.mode = mode

    def forward(self, x):
        from ..ops.manipulation import squeeze, unsqueeze

        x4 = unsqueeze(x, 2)  # [B, C, 1, L]
        fn = F.max_pool2d if self.mode == "max" else F.avg_pool2d
        out = fn(x4, (1, self.k), stride=(1, self.s),
                 padding=(0, self.p))
        return squeeze(out, 2)


class MaxPool1D(_Pool1D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, **kw):
        super().__init__(kernel_size, stride, padding, "max", ceil_mode)


class AvgPool1D(_Pool1D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, **kw):
        super().__init__(kernel_size, stride, padding, "avg", ceil_mode)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x):
        return _extra.max_pool3d(x, self.k, self.s, self.p)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x):
        return _extra.avg_pool3d(x, self.k, self.s, self.p)


@def_op("adaptive_pool_nd")
def _adaptive_pool_nd(x, out_sizes, mode):
    """Adaptive pool over the trailing len(out_sizes) spatial dims:
    each output cell reduces its floor/ceil-bounded input window
    (matches the reference's bin math)."""
    spatial0 = x.ndim - len(out_sizes)
    out = x
    for i, osz in enumerate(out_sizes):
        ax = spatial0 + i
        isz = out.shape[ax]
        osz = int(osz)
        starts = [int(np.floor(j * isz / osz)) for j in range(osz)]
        ends = [int(np.ceil((j + 1) * isz / osz)) for j in range(osz)]
        slabs = []
        for st, en in zip(starts, ends):
            sl = lax.slice_in_dim(out, st, en, axis=ax)
            red = jnp.max(sl, axis=ax, keepdims=True) if mode == "max" \
                else jnp.mean(sl, axis=ax, keepdims=True)
            slabs.append(red)
        out = jnp.concatenate(slabs, axis=ax)
    return out


class _AdaptivePool(Layer):
    def __init__(self, output_size, nd, mode):
        super().__init__()
        self.out_sizes = _pair(output_size, nd)
        self.mode = mode

    def forward(self, x):
        return _adaptive_pool_nd(x, self.out_sizes, self.mode)


class AdaptiveAvgPool1D(_AdaptivePool):
    def __init__(self, output_size, **kw):
        super().__init__(output_size, 1, "avg")


class AdaptiveMaxPool1D(_AdaptivePool):
    def __init__(self, output_size, **kw):
        super().__init__(output_size, 1, "max")


class AdaptiveAvgPool3D(_AdaptivePool):
    def __init__(self, output_size, **kw):
        super().__init__(output_size, 3, "avg")


class AdaptiveMaxPool3D(_AdaptivePool):
    def __init__(self, output_size, **kw):
        super().__init__(output_size, 3, "max")


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x, indices, output_size=None):
        return _extra.max_unpool2d(x, indices, self.k, self.s, self.p,
                                   output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x, indices, output_size=None):
        from ..ops.manipulation import squeeze, unsqueeze

        out = _extra.max_unpool2d(
            unsqueeze(x, 2), unsqueeze(indices, 2), (1, self.k),
            (1, self.s or self.k), (0, self.p),
            None if output_size is None
            else (1, int(output_size[-1])))
        return squeeze(out, 2)


# ---------------------------------------------------------------------------
# convs (reference: nn/layer/conv.py)
# ---------------------------------------------------------------------------
class Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        enforce(padding_mode == "zeros",
                "Conv3D here supports padding_mode='zeros'")
        k = _pair(kernel_size, 3)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.groups = groups
        fan_in = in_channels * int(np.prod(k))
        from .initializer import Uniform

        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + k,
            default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def forward(self, x):
        return _extra.conv3d(x, self.weight, self.bias, self.stride,
                             self.padding, self.dilation, self.groups)


@def_op("conv_transpose_nd")
def _conv_transpose_nd(x, w, bias, stride, padding, nd, dilation=1,
                       output_padding=0):
    """Gradient-of-conv transposed convolution (reference: phi
    conv2d_transpose-family kernels). w is [in, out//groups, *k]."""
    stride = _pair(stride, nd)
    padding = _pair(padding, nd)
    dilation = _pair(dilation, nd)
    out_pad = _pair(output_padding, nd)
    dn_in = "NC" + "DHW"[3 - nd:]
    # paddle's [in, out, *k] weight IS the forward conv's OIW kernel
    # (the forward conv maps out_ch -> in_ch); transpose_kernel=True
    # makes conv_transpose compute that conv's input-VJP. The paddle/
    # torch "padding" p trims the output — in lax terms each side pads
    # d*(k-1) - p; output_padding extends the RIGHT side only.
    dims = lax.conv_dimension_numbers(
        x.shape, w.shape, (dn_in, "OI" + "DHW"[3 - nd:], dn_in))
    pads = []
    for i in range(nd):
        eff = dilation[i] * (w.shape[2 + i] - 1)
        pads.append((eff - padding[i],
                     eff - padding[i] + out_pad[i]))
    out = lax.conv_transpose(
        x, w, stride, pads, rhs_dilation=dilation,
        dimension_numbers=dims, transpose_kernel=True)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


class Conv1DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 bias_attr=None, **kw):
        super().__init__()
        enforce(groups == 1, "Conv1DTranspose here supports groups=1")
        self.stride, self.padding = stride, padding
        self.dilation, self.output_padding = dilation, output_padding
        from .initializer import Uniform

        bound = 1.0 / math.sqrt(in_channels * int(kernel_size))
        self.weight = self.create_parameter(
            (in_channels, out_channels, int(kernel_size)),
            default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def forward(self, x):
        return _conv_transpose_nd(x, self.weight, self.bias, self.stride,
                                  self.padding, 1, self.dilation,
                                  self.output_padding)


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 bias_attr=None, **kw):
        super().__init__()
        enforce(groups == 1, "Conv3DTranspose here supports groups=1")
        k = _pair(kernel_size, 3)
        self.stride, self.padding = stride, padding
        self.dilation, self.output_padding = dilation, output_padding
        from .initializer import Uniform

        bound = 1.0 / math.sqrt(in_channels * int(np.prod(k)))
        self.weight = self.create_parameter(
            (in_channels, out_channels) + k,
            default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def forward(self, x):
        return _conv_transpose_nd(x, self.weight, self.bias, self.stride,
                                  self.padding, 3, self.dilation,
                                  self.output_padding)


# ---------------------------------------------------------------------------
# dropout variants / padding / shapes (reference: nn/layer/common.py)
# ---------------------------------------------------------------------------
class Dropout3D(Layer):
    """Drops ENTIRE [D, H, W] channel slabs (reference: nn/layer/
    common.py Dropout3D) — a broadcastable [N, C, 1, 1, 1] mask."""

    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        if not self.training or not self.p:
            return x
        return _channel_dropout(x, float(self.p), _key_scalar())


@def_op("channel_dropout")
def _channel_dropout(x, p, key):
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape[:2])
    keep = keep.reshape(x.shape[:2] + (1,) * (x.ndim - 2))
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


class AlphaDropout(Layer):
    """SELU-consistent dropout (reference: nn/layer/common.py
    AlphaDropout): dropped units take -alpha' and the output is
    rescaled to preserve self-normalizing statistics."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        if not self.training or not self.p:
            return x
        return _alpha_dropout(x, float(self.p), _key_scalar())


def _key_scalar():
    from ..core import rng as _rng

    return _rng.get_key()


@def_op("alpha_dropout")
def _alpha_dropout(x, p, key):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


class _PadNd(Layer):
    def __init__(self, padding, mode, value, nd):
        super().__init__()
        self.padding = [int(padding)] * (2 * nd) if np.isscalar(padding) \
            else [int(p) for p in padding]
        self.mode = mode
        self.value = value

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__(padding, mode, value, 1)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__(padding, mode, value, 2)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, 3)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.out_shape = axis, list(shape)

    def forward(self, x):
        from ..ops.manipulation import reshape

        shp = x.shape
        ax = self.axis % len(shp)
        return reshape(x, shp[:ax] + self.out_shape + shp[ax + 1:])


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        return _extra.channel_shuffle(x, self.groups)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = downscale_factor

    def forward(self, x):
        return _extra.pixel_unshuffle(x, self.factor)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return _extra.fold(x, *self.a)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.a = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.a)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale = size, scale_factor

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale,
                             mode="nearest")


# ---------------------------------------------------------------------------
# activations / similarity (reference: nn/layer/activation.py, distance.py)
# ---------------------------------------------------------------------------
class LogSigmoid(Layer):
    def forward(self, x):
        return _extra.log_sigmoid(x)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return _extra.maxout(x, self.groups, self.axis)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return _extra.rrelu(x, self.lower, self.upper, self.training)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return _extra.thresholded_relu(x, self.threshold)


class Softmax2D(Layer):
    """Softmax over channels for each spatial position (reference:
    nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        enforce(x.ndim in (3, 4), "Softmax2D expects a 3-D/4-D input")
        return F.softmax(x, axis=-3)


class Bilinear(Layer):
    """out[b, o] = x1[b]^T W[o] x2[b] + bias (reference: nn/layer/
    common.py Bilinear over the phi bilinear kernel)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        from .initializer import Uniform

        bound = 1.0 / math.sqrt(in1_features)
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def forward(self, x1, x2):
        return _bilinear(x1, x2, self.weight, self.bias)


@def_op("bilinear")
def _bilinear(x1, x2, w, bias):
    out = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
    if bias is not None:
        out = out + bias
    return out.astype(x1.dtype)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.eps, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return _pairwise_distance(x, y, float(self.p), float(self.eps),
                                  bool(self.keepdim))


@def_op("pairwise_distance")
def _pairwise_distance(x, y, p, eps, keepdim):
    d = x - y + eps
    out = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return out[..., None] if keepdim else out


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor
    (reference: nn/layer/norm.py SpectralNorm over the phi
    spectral_norm kernel). Stateless per call: n_power_iterations run
    inside the traced op (a small lax.fori-style unroll)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim, self.power_iters, self.eps = dim, power_iters, eps
        w = int(np.prod(weight_shape)) // int(weight_shape[dim])
        from .initializer import Normal

        self.weight_u = self.create_parameter(
            (int(weight_shape[dim]),), default_initializer=Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            (w,), default_initializer=Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        out, u, v = _spectral_norm(weight, self.weight_u, self.weight_v,
                                   int(self.dim), int(self.power_iters),
                                   float(self.eps))
        # persist the power-iteration state (reference keeps u/v
        # buffers, so one iteration per step converges over training)
        self.weight_u._value = u._value
        self.weight_v._value = v._value
        return out


@def_op("spectral_norm")
def _spectral_norm(w, u, v, dim, power_iters, eps):
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)

    def norm(x):
        return x / (jnp.linalg.norm(x) + eps)

    for _ in range(max(power_iters, 1)):
        v = norm(mat.T @ u)
        u = norm(mat @ v)
    sigma = u @ mat @ v
    return w / sigma, lax.stop_gradient(u), lax.stop_gradient(v)


class _InstanceNormNd(Layer):
    """(reference: nn/layer/norm.py InstanceNorm1D/3D — the functional
    instance_norm is rank-generic, normalizing over dims 2..ndim)."""

    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format=None,
                 name=None):
        super().__init__()
        from .initializer import Constant

        self._epsilon = epsilon
        self.scale = self.create_parameter(
            (num_features,), default_initializer=Constant(1.0))
        self.bias = self.create_parameter((num_features,), is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.scale, self.bias,
                               epsilon=float(self._epsilon))


class InstanceNorm1D(_InstanceNormNd):
    pass


class InstanceNorm3D(_InstanceNormNd):
    pass


# ---------------------------------------------------------------------------
# loss zoo (reference: nn/layer/loss.py)
# ---------------------------------------------------------------------------
class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths,
                          label_lengths, self.blank, self.reduction,
                          norm_by_times)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return _extra.huber_loss(input, label, self.delta, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        cos = F.cosine_similarity(input1, input2, axis=-1)
        pos = 1.0 - cos
        neg = (cos - self.margin).clip(min=0.0)
        loss = pos * (label == 1).astype(cos.dtype) \
            + neg * (label == -1).astype(cos.dtype)
        return _reduce(loss, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.eps, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        var = variance.clip(min=self.eps)
        loss = 0.5 * (var.log() + (input - label) ** 2 / var)
        if self.full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        pos = input * (label == 1).astype(input.dtype)
        neg = (self.margin - input).clip(min=0.0) \
            * (label == -1).astype(input.dtype)
        return _reduce(pos + neg, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        from ..ops.extra import log_sigmoid

        loss = -(label * log_sigmoid(input)
                 + (1.0 - label) * log_sigmoid(-input))
        if self.weight is not None:
            loss = loss * self.weight
        return _reduce(loss.mean(axis=-1), self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin, self.reduction = p, margin, reduction
        self.weight = weight

    def forward(self, input, label):
        return _reduce(_multi_margin(input, label, self.weight,
                                     int(self.p), float(self.margin)),
                       self.reduction)


@def_op("multi_margin_loss")
def _multi_margin(x, label, weight, p, margin):
    C = x.shape[1]
    true = jnp.take_along_axis(x, label[:, None], axis=1)
    m = jnp.maximum(margin - true + x, 0.0) ** p
    if weight is not None:          # per-class weight of the TRUE class
        m = m * weight[label][:, None]
    mask = 1.0 - jax.nn.one_hot(label, C, dtype=x.dtype)
    return (m * mask).sum(axis=1) / C


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.eps, self.reduction = epsilon, reduction

    def forward(self, input, label):
        if self.log_input:
            loss = input.exp() - label * input
        else:
            loss = input - label * (input + self.eps).log()
        if self.full:
            # Stirling approximation for the label! term; clip the log
            # argument BEFORE multiplying so label=0 rows don't produce
            # 0 * -inf = NaN (masked out afterwards anyway)
            safe = label.clip(min=1.0)
            big = safe * safe.log() - safe \
                + 0.5 * (2 * math.pi * safe).log()
            loss = loss + big * (label > 1).astype(loss.dtype)
        return _reduce(loss, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        loss = (1.0 + (-label * input).exp()).log()
        return _reduce(loss, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.eps = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):
        dp = _pairwise_distance(input, positive, float(self.p),
                                float(self.eps), False)
        dn = _pairwise_distance(input, negative, float(self.p),
                                float(self.eps), False)
        if self.swap:
            dn2 = _pairwise_distance(positive, negative, float(self.p),
                                     float(self.eps), False)
            dn = dn.minimum(dn2)
        loss = (dp - dn + self.margin).clip(min=0.0)
        return _reduce(loss, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.fn = distance_function or (
            lambda a, b: _pairwise_distance(a, b, 2.0, 1e-6, False))
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        dp = self.fn(input, positive)
        dn = self.fn(input, negative)
        if self.swap:
            dn = dn.minimum(self.fn(positive, negative))
        loss = (dp - dn + self.margin).clip(min=0.0)
        return _reduce(loss, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: nn/layer/loss.py HSigmoidLoss over the phi
    hsigmoid_loss kernel; custom paths unsupported here). Each class
    maps to a leaf; the loss is the sum of binary logistic losses
    along its root path — O(log C) effective parameters touched per
    example, trained via dense masked matmuls."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        enforce(not is_custom, "custom trees are not supported here")
        enforce(num_classes >= 2, "num_classes must be >= 2")
        self.num_classes = num_classes
        D = num_classes - 1          # internal nodes
        from .initializer import Uniform

        bound = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            (D, feature_size), default_initializer=Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (D,), is_bias=True, default_initializer=Uniform(-bound, bound))
        self._codes, self._signs, self._mask = _build_tree_paths(
            num_classes)

    def forward(self, input, label):
        return _hsigmoid_loss(input, label, self.weight, self.bias,
                              self._codes, self._signs, self._mask)


def _tree_depth(num_classes):
    return int(math.ceil(math.log2(max(num_classes, 2)))) + 1


import functools


@functools.lru_cache(maxsize=32)
def _build_tree_paths(num_classes):
    """Per-class (node index, sign, mask) arrays for the complete
    binary tree (shared by the HSigmoidLoss layer and the functional
    form; cached — the functional form calls per step)."""
    codes = np.zeros((num_classes, _tree_depth(num_classes)), np.int32)
    signs = np.zeros_like(codes, np.float32)
    mask = np.zeros_like(codes, np.float32)
    for c in range(num_classes):
        node = c + num_classes  # leaves start at num_classes
        path = []
        while node > 1:
            parent = node // 2
            path.append((parent - 1, 1.0 if node % 2 == 0 else -1.0))
            node = parent
        for d, (idx, sgn) in enumerate(reversed(path)):
            codes[c, d] = idx
            signs[c, d] = sgn
            mask[c, d] = 1.0
    return jnp.asarray(codes), jnp.asarray(signs), jnp.asarray(mask)


@def_op("hsigmoid_loss")
def _hsigmoid_loss(x, label, w, bias, codes, signs, mask):
    idx = codes[label]                       # [B, D]
    sgn = signs[label]
    msk = mask[label]
    wn = w[idx]                              # [B, D, F]
    logit = jnp.einsum("bdf,bf->bd", wn, x)
    if bias is not None:
        logit = logit + bias[idx]
    # sum of -log sigmoid(sign * logit) along the path
    loss = -jax.nn.log_sigmoid(sgn * logit) * msk
    return loss.sum(axis=1, keepdims=True)
