"""Parameter initializers (paddle.nn.initializer analog).

(reference: python/paddle/nn/initializer/* — each initializer is an op that
fills a tensor; here each returns a fresh jax.Array from the global PRNG.)
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp

from ..core import rng
from ..core.dtype import convert_dtype

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "calculate_gain",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fans(shape: Sequence[int]):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight layout [fan_in, fan_out]
        return shape[0], shape[1]
    # conv [out_c, in_c, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=convert_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        from ..tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(v, dtype=convert_dtype(dtype))
        assert tuple(arr.shape) == tuple(shape), (arr.shape, shape)
        return arr


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        return (self.mean + self.std * jax.random.normal(
            rng.get_key(), tuple(shape), jnp.float32)).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        z = jax.random.truncated_normal(rng.get_key(), self.a, self.b,
                                        tuple(shape), jnp.float32)
        return (self.mean + self.std * z).astype(dt)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        return jax.random.uniform(rng.get_key(), tuple(shape), jnp.float32,
                                  self.low, self.high).astype(dt)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        dt = convert_dtype(dtype)
        return jax.random.uniform(rng.get_key(), tuple(shape), jnp.float32,
                                  -limit, limit).astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        dt = convert_dtype(dtype)
        return (std * jax.random.normal(rng.get_key(), tuple(shape),
                                        jnp.float32)).astype(dt)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        dt = convert_dtype(dtype)
        return jax.random.uniform(rng.get_key(), tuple(shape), jnp.float32,
                                  -limit, limit).astype(dt)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        dt = convert_dtype(dtype)
        return (std * jax.random.normal(rng.get_key(), tuple(shape),
                                        jnp.float32)).astype(dt)


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        dt = convert_dtype(dtype)
        return (self.gain * _orthogonal_rect(tuple(shape))).astype(dt)


def _orthogonal_rect(shape):
    rows = shape[0]
    cols = int(np.prod(shape[1:]))
    a = jax.random.normal(rng.get_key(), (max(rows, cols), min(rows, cols)),
                          jnp.float32)
    q, r = jnp.linalg.qr(a)
    q = q * jnp.sign(jnp.diag(r))
    if rows < cols:
        q = q.T
    return q[:rows, :cols].reshape(shape)


# ---------------------------------------------------------------------------
# Shard-local (windowed) keyed generation — the LazyGuard materialization
# path (reference capability: python/paddle/nn/initializer/lazy_init.py
# LazyGuard; here redesigned for sharded meshes: each process materializes
# ONLY its addressable shard windows, so host/device footprint is
# O(shard), not O(model)).
#
# Determinism contract: the value of a window depends only on (key,
# window start offsets) — every process materializing the same window of
# the same parameter produces identical bytes, with no cross-process
# communication. iid initializers generate directly at window shape;
# non-iid ones (Assign, Orthogonal) materialize the keyed full array and
# slice.
# ---------------------------------------------------------------------------


def _win_shape(full_shape, window):
    return tuple(s.indices(d)[1] - s.indices(d)[0]
                 for s, d in zip(window, full_shape))


def _win_key(key, full_shape, window):
    for s, d in zip(window, full_shape):
        key = jax.random.fold_in(key, s.indices(d)[0])
    return key


def _generate_window(init: Initializer, full_shape, window, dtype, key):
    """Materialize ``window`` of a ``full_shape`` parameter from ``key``."""
    full_shape = tuple(int(s) for s in full_shape)
    window = tuple(window)
    dt = convert_dtype(dtype)
    ws = _win_shape(full_shape, window)
    wk = _win_key(key, full_shape, window)

    if isinstance(init, Constant):
        return jnp.full(ws, init.value, dtype=dt)
    if isinstance(init, Normal):
        return (init.mean + init.std * jax.random.normal(
            wk, ws, jnp.float32)).astype(dt)
    if isinstance(init, TruncatedNormal):
        z = jax.random.truncated_normal(wk, init.a, init.b, ws, jnp.float32)
        return (init.mean + init.std * z).astype(dt)
    if isinstance(init, Uniform):
        return jax.random.uniform(wk, ws, jnp.float32, init.low,
                                  init.high).astype(dt)
    if isinstance(init, (XavierUniform, XavierNormal)):
        fi, fo = _fans(full_shape)      # fans from the FULL shape
        fi = init.fan_in if init.fan_in is not None else fi
        fo = init.fan_out if init.fan_out is not None else fo
        if isinstance(init, XavierUniform):
            limit = init.gain * math.sqrt(6.0 / (fi + fo))
            return jax.random.uniform(wk, ws, jnp.float32, -limit,
                                      limit).astype(dt)
        std = init.gain * math.sqrt(2.0 / (fi + fo))
        return (std * jax.random.normal(wk, ws, jnp.float32)).astype(dt)
    if isinstance(init, (KaimingUniform, KaimingNormal)):
        fi, _ = _fans(full_shape)
        fi = init.fan_in if init.fan_in is not None else fi
        gain = calculate_gain(init.nonlinearity, init.negative_slope)
        if isinstance(init, KaimingUniform):
            limit = gain * math.sqrt(3.0 / fi)
            return jax.random.uniform(wk, ws, jnp.float32, -limit,
                                      limit).astype(dt)
        std = gain / math.sqrt(fi)
        return (std * jax.random.normal(wk, ws, jnp.float32)).astype(dt)
    if isinstance(init, Assign):
        from ..tensor import Tensor as _T

        v = init.value
        v = v._value if isinstance(v, _T) else v
        return jnp.asarray(v, dtype=dt)[window]
    if isinstance(init, Orthogonal):
        # non-iid: keyed full materialization, then slice
        import numpy as _np

        rows = full_shape[0]
        cols = int(_np.prod(full_shape[1:]))
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)),
                              jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diag(r))
        if rows < cols:
            q = q.T
        full = (init.gain * q[:rows, :cols].reshape(full_shape)).astype(dt)
        return full[window]
    raise NotImplementedError(
        f"{type(init).__name__} has no shard-local keyed generation; "
        "initialize eagerly (outside LazyGuard)")
