"""Normalisation layers (reference: python/paddle/nn/layer/norm.py).

BatchNorm keeps running stats as buffers updated functionally — under a
traced train step the new stats come out as traced values and are written
back to the buffer tensors (value-swap), so the whole step still compiles
to one XLA program.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.param_attr import ParamAttr
from ..tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = ["BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "BatchNorm",
           "LayerNorm", "RMSNorm", "GroupNorm", "InstanceNorm2D",
           "SyncBatchNorm", "LocalResponseNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,),
                                                       self._dtype)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,),
                                                          self._dtype)))

    def forward(self, x):
        training = self.training and not self._use_global_stats
        out, new_mean, new_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=bool(training), momentum=float(self._momentum),
            epsilon=float(self._epsilon), data_format=self._data_format)
        if training:
            self._mean._value = new_mean._value
            self._variance._value = new_var._value
        return out

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN: batch stats all-reduced over the data-parallel
    group (reference: python/paddle/nn/layer/norm.py SyncBatchNorm backed by
    sync_batch_norm CUDA kernel; here stats ride XLA psum when inside an
    SPMD region)."""

    def forward(self, x):
        if not self.training:
            return super().forward(x)
        from ..distributed import collective as C

        if not C.in_spmd_region():
            return super().forward(x)
        axes = (0, 2, 3) if x.ndim == 4 else ((0,) if x.ndim == 2 else (0, 2))
        from ..ops import math as M

        mean = M.mean(x, axis=axes)
        meansq = M.mean(x * x, axis=axes)
        mean = C.all_reduce_mean_value(mean)
        meansq = C.all_reduce_mean_value(meansq)
        var = meansq - mean * mean
        inv = (var + self._epsilon) ** -0.5
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = (x - mean.reshape(shape)) * inv.reshape(shape)
        if self.weight is not None:
            out = out * self.weight.reshape(shape)
        if self.bias is not None:
            out = out + self.bias.reshape(shape)
        self._mean._value = (self._momentum * self._mean._value
                             + (1 - self._momentum) * mean._value)
        self._variance._value = (self._momentum * self._variance._value
                                 + (1 - self._momentum) * var._value)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            out.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = (self.create_parameter(
            self._normalized_shape, attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0))
            if weight_attr is not False else None)
        self.bias = (self.create_parameter(
            self._normalized_shape, attr=ParamAttr._to_attr(bias_attr),
            is_bias=True) if bias_attr is not False else None)

    def forward(self, x):
        begin = x.ndim - len(self._normalized_shape)
        return F.layer_norm(x, self.weight, self.bias,
                            epsilon=float(self._epsilon), begin_norm_axis=begin)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """(reference kernel: phi/kernels/gpu/rms_norm_kernel.cu; used by the
    Llama family via paddle.incubate.nn.functional.fused_rms_norm)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        begin = x.ndim - len(self._normalized_shape)
        return F.rms_norm(x, self.weight, epsilon=float(self._epsilon),
                          begin_norm_axis=begin)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (num_channels,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_channels,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.weight, self.bias,
                            epsilon=float(self._epsilon),
                            groups=self._num_groups)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = self.create_parameter(
            (num_features,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.scale, self.bias,
                               epsilon=float(self._epsilon))


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        from ..ops import math as M
        from ..ops import nn_ops as N
        import jax

        sq = x * x
        # average over a channel window
        pad = self.size // 2
        val = N.avg_pool2d(
            sq.transpose(perm=(0, 2, 1, 3)) if x.ndim == 4 else sq,
            kernel_size=(self.size, 1), stride=1, padding=(pad, 0),
            exclusive=False)
        if x.ndim == 4:
            val = val.transpose(perm=(0, 2, 1, 3))
        return x / (self.k + self.alpha * val * self.size) ** self.beta
