"""Functional NN API (paddle.nn.functional analog).

Mostly re-exports the registered ops; stateful bits (dropout keys) are
resolved here so the underlying kernels stay pure.
(reference: python/paddle/nn/functional/*, incl. flash_attention.py:147.)
"""
from __future__ import annotations

from ..core import rng
from ..ops import nn_ops as _ops
from ..ops.nn_ops import (  # noqa: F401
    relu, relu6, leaky_relu, elu, selu, celu, gelu, silu, swish, mish,
    sigmoid, hardsigmoid, hardswish, hardtanh, softplus, softsign,
    tanhshrink, hardshrink, softshrink, prelu, glu, softmax, log_softmax,
    linear, fused_gemm_epilogue,
    conv1d, conv2d, conv2d_transpose,
    max_pool2d, avg_pool2d, adaptive_avg_pool2d, adaptive_max_pool2d,
    interpolate, unfold,
    layer_norm, rms_norm, group_norm, instance_norm, batch_norm,
    fused_layer_norm_residual,
    softmax_with_cross_entropy, mse_loss, l1_loss, smooth_l1_loss, nll_loss,
    binary_cross_entropy, binary_cross_entropy_with_logits, kl_div,
    cosine_similarity, label_smooth, temporal_shift, pixel_shuffle,
    fused_rope,
)
from ..ops.manipulation import one_hot, pad  # noqa: F401
from ..ops.math import tanh  # noqa: F401

__all__ = [n for n in dir() if not n.startswith("_")]


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x
    return _ops.dropout(x, rng.get_key(), p=float(p), training=True, mode=mode)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return dropout(x, p=p, training=training)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _ops.embedding(x, weight, padding_idx=padding_idx)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  label_smoothing=0.0, use_softmax=True, name=None):
    return _ops.cross_entropy_loss(
        input, label, weight=weight, soft_label=bool(soft_label),
        ignore_index=int(ignore_index), reduction=reduction, axis=int(axis),
        label_smoothing=float(label_smoothing))


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    from ..core import rng as _rng

    p = float(dropout_p) if training else 0.0
    return _ops.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=p,
        is_causal=bool(is_causal),
        dropout_key=_rng.get_key() if p else None)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity
    (reference: python/paddle/nn/functional/flash_attention.py:147).
    Layout [batch, seqlen, num_heads, head_dim]. On TPU this routes to the
    Pallas flash kernel; XLA fallback otherwise."""
    from ..core import rng as _rng
    from ..ops import attention as _attn

    p = float(dropout) if training else 0.0
    out = _attn.flash_attention(query, key, value, causal=bool(causal),
                                dropout=p,
                                dropout_key=_rng.get_key() if p else None)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Packed varlen flash attention (reference: python/paddle/nn/
    functional/flash_attention.py:384 flash_attn_unpadded). q/k/v are
    [total_tokens, H, D]; cu_seqlens mark sequence boundaries. On TPU
    the Pallas flash kernel runs with segment-id masking; elsewhere a
    dense segment mask. Returns (out, softmax) like the reference."""
    from ..core import rng as _rng
    from ..ops import attention as _attn

    p = float(dropout) if training else 0.0
    out = _attn.flash_attn_varlen(
        query, key, value, cu_seqlens_q, cu_seqlens_k, causal=bool(causal),
        scale=scale, dropout=p, dropout_key=_rng.get_key() if p else None)
    return out, None


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    from ..ops import math as _m

    norm = _m.norm(x, p=float(p), axis=axis, keepdim=True)
    return x / _m.clip(norm, min=epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):
    from ..ops import math as _m

    return 0.0 - label * _m.log(input + epsilon) - (
        1.0 - label) * _m.log(1.0 - input + epsilon)


def square_error_cost(input, label):
    return (input - label) * (input - label)


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    import jax.numpy as jnp

    from ..core.dispatch import def_op
    return _sequence_mask(lengths, maxlen=maxlen, dtype=str(dtype))


from ..core.dispatch import def_op as _def_op
import jax.numpy as _jnp


@_def_op("sequence_mask", differentiable=False)
def _sequence_mask(lengths, maxlen=None, dtype="int64"):
    m = maxlen if maxlen is not None else int(lengths.max())
    ar = _jnp.arange(m)
    return (ar[None, :] < lengths[:, None]).astype(_jnp.dtype(dtype) if dtype != "int64" else _jnp.int64)
