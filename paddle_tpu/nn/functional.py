"""Functional NN API (paddle.nn.functional analog).

Mostly re-exports the registered ops; stateful bits (dropout keys) are
resolved here so the underlying kernels stay pure.
(reference: python/paddle/nn/functional/*, incl. flash_attention.py:147.)
"""
from __future__ import annotations

from ..core import rng
from ..ops import nn_ops as _ops
from ..ops.nn_ops import (  # noqa: F401
    relu, relu6, leaky_relu, elu, selu, celu, gelu, silu, swish, mish,
    sigmoid, hardsigmoid, hardswish, hardtanh, softplus, softsign,
    tanhshrink, hardshrink, softshrink, prelu, glu, softmax, log_softmax,
    linear, fused_gemm_epilogue,
    conv1d, conv2d, conv2d_transpose,
    max_pool2d, avg_pool2d, adaptive_avg_pool2d, adaptive_max_pool2d,
    interpolate, unfold,
    layer_norm, rms_norm, group_norm, instance_norm, batch_norm,
    fused_layer_norm_residual,
    softmax_with_cross_entropy, mse_loss, l1_loss, smooth_l1_loss, nll_loss,
    binary_cross_entropy, binary_cross_entropy_with_logits, kl_div,
    cosine_similarity, label_smooth, temporal_shift, pixel_shuffle,
    fused_rope,
)
from ..ops.manipulation import one_hot, pad  # noqa: F401
from ..ops.math import tanh  # noqa: F401

__all__ = [n for n in dir() if not n.startswith("_")]


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x
    return _ops.dropout(x, rng.get_key(), p=float(p), training=True, mode=mode)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return dropout(x, p=p, training=training)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _ops.embedding(x, weight, padding_idx=padding_idx)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  label_smoothing=0.0, use_softmax=True, name=None):
    return _ops.cross_entropy_loss(
        input, label, weight=weight, soft_label=bool(soft_label),
        ignore_index=int(ignore_index), reduction=reduction, axis=int(axis),
        label_smoothing=float(label_smoothing))


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    from ..core import rng as _rng

    p = float(dropout_p) if training else 0.0
    return _ops.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=p,
        is_causal=bool(is_causal),
        dropout_key=_rng.get_key() if p else None)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity
    (reference: python/paddle/nn/functional/flash_attention.py:147).
    Layout [batch, seqlen, num_heads, head_dim]. On TPU this routes to the
    Pallas flash kernel; XLA fallback otherwise."""
    from ..core import rng as _rng
    from ..ops import attention as _attn

    p = float(dropout) if training else 0.0
    out = _attn.flash_attention(query, key, value, causal=bool(causal),
                                dropout=p,
                                dropout_key=_rng.get_key() if p else None)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Packed varlen flash attention (reference: python/paddle/nn/
    functional/flash_attention.py:384 flash_attn_unpadded). q/k/v are
    [total_tokens, H, D]; cu_seqlens mark sequence boundaries. On TPU
    the Pallas flash kernel runs with segment-id masking; elsewhere a
    dense segment mask. Returns (out, softmax) like the reference."""
    from ..core import rng as _rng
    from ..ops import attention as _attn

    p = float(dropout) if training else 0.0
    out = _attn.flash_attn_varlen(
        query, key, value, cu_seqlens_q, cu_seqlens_k, causal=bool(causal),
        scale=scale, dropout=p, dropout_key=_rng.get_key() if p else None)
    return out, None


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    from ..ops import math as _m

    norm = _m.norm(x, p=float(p), axis=axis, keepdim=True)
    return x / _m.clip(norm, min=epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):
    from ..ops import math as _m

    return 0.0 - label * _m.log(input + epsilon) - (
        1.0 - label) * _m.log(1.0 - input + epsilon)


def square_error_cost(input, label):
    return (input - label) * (input - label)


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    import jax.numpy as jnp

    from ..core.dispatch import def_op
    return _sequence_mask(lengths, maxlen=maxlen, dtype=str(dtype))


from ..core.dispatch import def_op as _def_op
import jax.numpy as _jnp


@_def_op("sequence_mask", differentiable=False)
def _sequence_mask(lengths, maxlen=None, dtype="int64"):
    m = maxlen if maxlen is not None else int(lengths.max())
    ar = _jnp.arange(m)
    return (ar[None, :] < lengths[:, None]).astype(_jnp.dtype(dtype) if dtype != "int64" else _jnp.int64)


# ---------------------------------------------------------------------------
# spatial sampling (reference: phi grid_sample / affine_grid kernels)
# ---------------------------------------------------------------------------
def _gs_unnormalize(coord, size, align_corners):
    import jax.numpy as jnp

    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _gs_reflect(coord, size, align_corners):
    import jax.numpy as jnp

    # reflect into the valid span, matching torch/paddle semantics
    if align_corners:
        span = 2.0 * (size - 1)
        lo = 0.0
    else:
        span = 2.0 * size
        lo = -0.5
    if span == 0:
        return jnp.zeros_like(coord)
    c = jnp.abs((coord - lo) % span)
    return jnp.where(c > span / 2, span - c, c) + lo


def _grid_sample_kernel(x, grid, mode, padding_mode, align_corners):
    import jax.numpy as jnp

    N, C, H, W = x.shape
    gx = _gs_unnormalize(grid[..., 0].astype(jnp.float32), W,
                         align_corners)
    gy = _gs_unnormalize(grid[..., 1].astype(jnp.float32), H,
                         align_corners)
    if padding_mode == "reflection":
        gx = _gs_reflect(gx, W, align_corners)
        gy = _gs_reflect(gy, H, align_corners)
    if padding_mode in ("border", "reflection"):
        gx = jnp.clip(gx, 0, W - 1)
        gy = jnp.clip(gy, 0, H - 1)

    def fetch(ix, iy):
        """x[n, :, iy, ix] with zero padding outside."""
        inb = ((ix >= 0) & (ix <= W - 1) & (iy >= 0)
               & (iy <= H - 1))
        ixc = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        lin = iyc * W + ixc                        # [N, Hg, Wg]
        flat = x.reshape(N, C, H * W)
        g = jnp.take_along_axis(
            flat, lin.reshape(N, 1, -1).astype(jnp.int32), axis=2)
        g = g.reshape(N, C, *lin.shape[1:])
        return g * inb[:, None].astype(x.dtype)

    if mode == "nearest":
        return fetch(jnp.round(gx), jnp.round(gy))
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx1 = (gx - x0).astype(x.dtype)[:, None]
    wy1 = (gy - y0).astype(x.dtype)[:, None]
    wx0, wy0 = 1 - wx1, 1 - wy1
    return (fetch(x0, y0) * wx0 * wy0 + fetch(x0 + 1, y0) * wx1 * wy0
            + fetch(x0, y0 + 1) * wx0 * wy1
            + fetch(x0 + 1, y0 + 1) * wx1 * wy1)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at normalized grid locations (reference:
    nn/functional/vision.py grid_sample over phi grid_sample kernel) —
    gathers + bilinear weights, differentiable, all HLOs."""
    from ..core.dispatch import def_op as _def_op

    global _grid_sample_op
    if "_grid_sample_op" not in globals():
        _grid_sample_op = _def_op("grid_sample")(_grid_sample_kernel)
    return _grid_sample_op(x, grid, str(mode), str(padding_mode),
                           bool(align_corners))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2-D affine sampling grid from theta [N, 2, 3] (reference:
    nn/functional/vision.py affine_grid)."""
    from ..core.dispatch import def_op as _def_op

    global _affine_grid_op
    if "_affine_grid_op" not in globals():
        import jax.numpy as jnp

        def _kernel(theta, H, W, align_corners):
            if align_corners:
                ys = jnp.linspace(-1.0, 1.0, H)
                xs = jnp.linspace(-1.0, 1.0, W)
            else:
                ys = (jnp.arange(H) * 2.0 + 1.0) / H - 1.0
                xs = (jnp.arange(W) * 2.0 + 1.0) / W - 1.0
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [H,W,3]
            out = jnp.einsum("hwk,nck->nhwc", base,
                             theta.astype(jnp.float32))
            return out.astype(theta.dtype)

        _affine_grid_op = _def_op("affine_grid")(_kernel)
    H, W = int(out_shape[-2]), int(out_shape[-1])
    return _affine_grid_op(theta, H, W, bool(align_corners))


# ---------------------------------------------------------------------------
# CTC loss (reference: warpctc op, python nn/functional/loss.py ctc_loss)
# ---------------------------------------------------------------------------
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss.

    TPU design: the alpha (forward) recursion over the extended label
    sequence [blank, l1, blank, l2, ...] is ONE lax.scan over time in
    log space — no per-step host dispatch, static shapes, differentiable
    through the scan (the reference dynloads warp-ctc CUDA:
    paddle/phi/kernels/gpu/warpctc_kernel.cu).

    log_probs: [T, B, C] unscaled logits ("unscaled probability
    sequence", the reference warpctc contract — it integrates a native
    softmax); labels: [B, L] padded. A log_softmax is applied inside the
    kernel, so already-normalized log-probabilities (the torch
    convention) are ALSO accepted unchanged: log_softmax is exactly
    idempotent on them (logsumexp of log-probs is 0).
    """
    from ..core.dispatch import def_op as _def_op

    global _ctc_op
    if "_ctc_op" not in globals():
        import jax.numpy as jnp
        from jax import lax

        NEG = -1e30

        def _kernel(log_probs, labels, input_lengths, label_lengths,
                    blank):
            import jax

            # Reference contract: inputs are unscaled logits (warp-ctc
            # integrates the softmax). No-op for normalized log-probs.
            log_probs = jax.nn.log_softmax(log_probs, axis=-1)
            T, B, C = log_probs.shape
            L = labels.shape[1]
            S = 2 * L + 1
            # extended sequence: blank at even positions
            ext = jnp.full((B, S), blank, labels.dtype)
            ext = ext.at[:, 1::2].set(labels)
            # can skip from s-2 to s when ext[s] != blank and != ext[s-2]
            ext_prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)),
                                constant_values=-1)
            can_skip = (ext != blank) & (ext != ext_prev2)       # [B, S]

            emit0 = jnp.take_along_axis(log_probs[0], ext, axis=1)
            alpha0 = jnp.full((B, S), NEG)
            alpha0 = alpha0.at[:, 0].set(emit0[:, 0])
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(label_lengths > 0, emit0[:, 1], NEG))

            def lse(*xs):
                stacked = jnp.stack(xs)
                m = jnp.max(stacked, axis=0)
                dead = m <= NEG / 2
                safe_m = jnp.where(dead, 0.0, m)
                # double-where: zero the exp args on the dead branch so
                # log never sees 0 and the where-VJP never sees NaN
                args = jnp.where(dead[None], 0.0, stacked - safe_m)
                out = safe_m + jnp.log(jnp.sum(jnp.exp(args), axis=0))
                return jnp.where(dead, NEG, out)

            def step(alpha, t):
                emit = jnp.take_along_axis(log_probs[t], ext, axis=1)
                a1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)),
                             constant_values=NEG)
                a2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)),
                             constant_values=NEG)
                a2 = jnp.where(can_skip, a2, NEG)
                new = lse(alpha, a1, a2) + emit
                # freeze past each sequence's input length
                live = (t < input_lengths)[:, None]
                return jnp.where(live, new, alpha), None

            alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
            # final: last blank or last label position
            send = 2 * label_lengths          # index of final blank
            last_blank = jnp.take_along_axis(alpha, send[:, None],
                                             axis=1)[:, 0]
            last_lab = jnp.take_along_axis(
                alpha, jnp.maximum(send - 1, 0)[:, None], axis=1)[:, 0]
            last_lab = jnp.where(label_lengths > 0, last_lab, NEG)
            return -lse(last_blank, last_lab)

        _ctc_op = _def_op("warpctc")(_kernel)
    from ..tensor import Tensor

    il = input_lengths if isinstance(input_lengths, Tensor) else \
        __import__("paddle_tpu").to_tensor(input_lengths)
    ll = label_lengths if isinstance(label_lengths, Tensor) else \
        __import__("paddle_tpu").to_tensor(label_lengths)
    loss = _ctc_op(log_probs, labels, il, ll, int(blank))
    if norm_by_times:
        loss = loss / il.astype("float32")
    if reduction == "mean":
        return (loss / ll.astype("float32")).mean()
    if reduction == "sum":
        return loss.sum()
    return loss


# functional tail (delegations + transducer/focal/gumbel math)
from .functional_extra import *  # noqa: F401,F403,E402
