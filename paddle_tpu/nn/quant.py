"""Weight-only quantization for serving (paddle.nn.quant analog).

(reference: python/paddle/nn/quant/quantized_linear.py —
weight_quantize/weight_dequantize/weight_only_linear/llm_int8_linear
over the weight_only_linear / llm_int8_matmul CUDA kernels,
phi/kernels/fusion/gpu/.)

TPU design: decode-time generation is weight-HBM-bandwidth-bound, so
the win comes from STORING weights int8/int4 in HBM and letting XLA
fuse the int8->bf16 convert into the matmul operand read — the MXU
consumes bf16 tiles dequantized in VMEM, HBM traffic is halved (int8)
or quartered (int4). Per-output-channel scales are applied AFTER the
matmul (mathematically identical, one multiply per output element), so
no dequantized weight copy ever exists in HBM.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.enforce import enforce
from ..tensor import Parameter, Tensor
from .layer import Layer

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "WeightOnlyLinear", "quantize_for_serving"]

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Per-output-channel symmetric quantization of a [in, out] weight.

    Returns (out, scale): ``out`` int8 with shape [out, in] (the
    reference's transposed layout; int4 packs two values per int8 ->
    [out, in//2]), ``scale`` float32 [out].
    """
    enforce(algo in _ALGOS, lambda: f"algo must be one of {_ALGOS}")
    w = _val(x).astype(jnp.float32).T          # [out, in]
    bits = 4 if algo == "weight_only_int4" else 8
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(w), axis=1) / qmax  # [out]
    q = jnp.round(w / jnp.maximum(scale, 1e-10)[:, None])
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        enforce(q.shape[1] % 2 == 0,
                lambda: "int4 needs an even input dimension")
        lo = q[:, 0::2] & 0x0F                  # low nibble
        hi = (q[:, 1::2] & 0x0F) << 4           # high nibble
        q = (lo | hi).astype(jnp.int8)          # [out, in//2]
    return (Tensor(q, stop_gradient=True),
            Tensor(scale.astype(jnp.float32), stop_gradient=True))


def _unpack_int4(q):
    """[out, in//2] packed int8 -> [out, in] int8 in {-8..7} (sign
    extension via shift: XLA fuses this into the consumer)."""
    lo = (q << 4) >> 4                          # sign-extend low nibble
    hi = q >> 4                                 # arithmetic shift: high
    out = jnp.stack([lo, hi], axis=-1)          # [out, in//2, 2]
    return out.reshape(q.shape[0], q.shape[1] * 2)


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float16", group_size=-1):
    """Inverse of weight_quantize: back to [in, out] float."""
    enforce(algo in _ALGOS, lambda: f"algo must be one of {_ALGOS}")
    q = _val(x)
    if algo == "weight_only_int4":
        q = _unpack_int4(q)
    w = q.astype(jnp.float32) * _val(scale)[:, None]
    return Tensor(w.T.astype(jnp.dtype(out_dtype)), stop_gradient=True)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight).T + bias with int8/int4 weights resident
    in HBM; the convert fuses into the MXU operand read and the
    per-channel scale applies post-matmul."""
    xv = _val(x)
    q = _val(weight)                            # [out, in] (int4: packed)
    if weight_dtype == "int4":
        q = _unpack_int4(q)
    scale = _val(weight_scale).astype(jnp.float32)
    acc = jnp.einsum("...k,ok->...o", xv, q.astype(xv.dtype),
                     preferred_element_type=jnp.float32)
    out = acc * scale
    if bias is not None:
        out = out + _val(bias).astype(jnp.float32)
    # inference-only op (no grad tape is recorded for it)
    return Tensor(out.astype(xv.dtype), stop_gradient=True)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8-style decomposition: activation feature columns whose
    absmax exceeds ``threshold`` run in floating point, the rest as
    int8 x int8 -> int32 on the MXU (reference:
    phi/kernels/fusion/gpu/llm_int8_matmul_kernel.cu)."""
    xv = _val(x)
    q = _val(weight)                            # [out, in] int8
    scale = _val(weight_scale).astype(jnp.float32)
    xf = xv.astype(jnp.float32)
    col_amax = jnp.max(jnp.abs(xf), axis=tuple(range(xf.ndim - 1)))
    outlier = col_amax > threshold              # [in]
    # int8 path: quantize non-outlier activations per-token
    x_in = jnp.where(outlier, 0.0, xf)
    a_scale = jnp.maximum(
        jnp.max(jnp.abs(x_in), axis=-1, keepdims=True), 1e-10) / 127.0
    xq = jnp.clip(jnp.round(x_in / a_scale), -127, 127).astype(jnp.int8)
    acc = jnp.einsum("...k,ok->...o", xq, q,
                     preferred_element_type=jnp.int32)
    y_int = acc.astype(jnp.float32) * a_scale * scale
    # fp path for outlier columns against the dequantized weight; a
    # lax.cond skips the whole matmul at runtime when no column is an
    # outlier (the common well-scaled case)
    import jax

    def _fp_branch(operands):
        xf_, q_, scale_ = operands
        x_out = jnp.where(outlier, xf_, 0.0)
        return jnp.einsum("...k,ok->...o", x_out,
                          q_.astype(jnp.float32) * scale_[:, None])

    y_fp = jax.lax.cond(
        jnp.any(outlier), _fp_branch,
        lambda operands: jnp.zeros(y_int.shape, jnp.float32),
        (xf, q, scale))
    out = y_int + y_fp
    if bias is not None:
        out = out + _val(bias).astype(jnp.float32)
    return Tensor(out.astype(xv.dtype), stop_gradient=True)


class WeightOnlyLinear(Layer):
    """Serving Linear with int8/int4 weights in HBM (the layer form of
    ``weight_only_linear``; swap target of ``quantize_for_serving``).

    Registers the quantized weight and scale as non-trainable
    Parameters so compiled serving programs (Predictor) bind them as
    runtime buffers rather than baking them into the executable.
    """

    def __init__(self, inner, algo="weight_only_int8"):
        super().__init__()
        enforce(algo in ("weight_only_int8", "weight_only_int4"),
                lambda: f"unsupported algo {algo!r}")
        self.algo = algo
        self.weight_dtype = "int4" if algo.endswith("int4") else "int8"
        q, s = weight_quantize(inner.weight, algo)
        self.weight_quant = Parameter(q._value, trainable=False)
        self.weight_scale = Parameter(s._value, trainable=False)
        self.bias = inner.bias
        self.name = getattr(inner, "name", None)

    def forward(self, x):
        return weight_only_linear(x, self.weight_quant, self.bias,
                                  self.weight_scale, self.weight_dtype)


def quantize_for_serving(model, algo="weight_only_int8", skip=()):
    """Swap every Linear-like layer in ``model`` (in place) for
    WeightOnlyLinear.

    Covers nn.Linear and the TP layers (Column/RowParallelLinear) when
    their mp degree is 1 — at mp>1 the fp collective path is kept, since
    WeightOnlyLinear carries no mp collectives. ``skip``: layer-name
    fragments to keep in full precision (e.g. the LM head). Returns the
    model.
    """
    from .common import Linear

    def _swappable(sub):
        if isinstance(sub, Linear):
            return True
        if type(sub).__name__ in ("ColumnParallelLinear",
                                  "RowParallelLinear"):
            return not getattr(sub, "is_mp", False)
        return False

    def _swap(layer, prefix=""):
        for name in list(layer._sub_layers):
            sub = layer._sub_layers[name]
            full = f"{prefix}.{name}" if prefix else name
            if _swappable(sub) and not any(s in full for s in skip):
                if algo.endswith("int4") and sub.weight._value.shape[0] % 2:
                    import warnings

                    warnings.warn(
                        f"quantize_for_serving: {full} kept in full "
                        f"precision (odd in_features "
                        f"{sub.weight._value.shape[0]} cannot pack int4 "
                        f"nibbles)", stacklevel=2)
                    continue
                layer._sub_layers[name] = WeightOnlyLinear(sub, algo)
            else:
                _swap(sub, full)
    _swap(model)
    return model
