"""Convolution layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from ..framework.param_attr import ParamAttr
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = ["Conv1D", "Conv2D", "Conv2DTranspose"]


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, ndim,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * ndim
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels // groups * int(np.prod(kernel_size))
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + self._kernel_size,
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter(
            (out_channels,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}, "
                f"padding={self._padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        pad = self._padding
        if isinstance(pad, (list, tuple)):
            pad = tuple(tuple(p) if isinstance(p, (list, tuple)) else p for p in pad)
        return F.conv2d(x, self.weight, self.bias, stride=self._stride,
                        padding=pad, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels * int(np.prod(kernel_size)) // groups
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + tuple(kernel_size),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter(
            (out_channels,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.conv2d_transpose(
            x, self.weight, self.bias, stride=self._stride,
            padding=self._padding, output_padding=self._output_padding,
            dilation=self._dilation, groups=self._groups,
            data_format=self._data_format)
