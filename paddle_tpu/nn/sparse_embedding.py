"""Sparse-gradient embedding lookup (reference: nn.Embedding(sparse=
True) → phi/kernels/cpu|gpu/embedding_sparse_grad_kernel.cc — the
weight gradient comes back as a SelectedRows of only the looked-up
rows, not a dense (vocab, dim) tensor).

TPU design: the forward is a plain gather; the backward hands the
autograd engine a ``SelectedRows(rows=ids, values=upstream_grad)``
directly — O(batch·dim) instead of O(vocab·dim) — which the engine
accumulates leaf-side and the optimizer applies as a row scatter
(lazy per-row moments for Adam). Only valid for a LEAF weight
(a Parameter): SelectedRows cannot flow through further grad kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd import engine
from ..core.enforce import enforce
from ..framework.selected_rows import SelectedRows
from ..tensor import Tensor

__all__ = ["sparse_embedding"]


def sparse_embedding(ids, weight, padding_idx=None):
    iv = ids._value if isinstance(ids, Tensor) else jnp.asarray(ids)
    out_val = weight._value[iv]
    out = Tensor(out_val, stop_gradient=True)
    if engine.is_grad_enabled() and not weight.stop_gradient:
        enforce(weight._grad_node is None,
                "Embedding(sparse=True) requires a leaf weight "
                "(a Parameter): a SelectedRows gradient cannot flow "
                "through upstream ops (e.g. an amp cast); use "
                "sparse=False there")
        out.stop_gradient = False
        height, dim = weight.shape[0], weight.shape[1]

        def backward_fn(gout):
            rows = iv.reshape(-1)
            vals = gout.reshape(-1, dim)
            if padding_idx is not None:
                vals = jnp.where((rows == padding_idx)[:, None],
                                 jnp.zeros_like(vals), vals)
            return (SelectedRows(rows, vals, height),)

        engine.record_custom("sparse_embedding", backward_fn,
                             [weight], [out], out_val)
    return out
