"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from ..framework.param_attr import ParamAttr
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "ELU", "SELU", "CELU", "GELU",
           "Silu", "Swish", "Mish", "Sigmoid", "Hardsigmoid", "Hardswish",
           "Hardtanh", "Softplus", "Softsign", "Tanhshrink", "Hardshrink",
           "Softshrink", "PReLU", "Softmax", "LogSoftmax", "Tanh", "GLU"]


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return getattr(F, fn_name)(x, **fixed)

    return _Act


ReLU = type("ReLU", (_simple("relu"),), {})
ReLU6 = type("ReLU6", (_simple("relu6"),), {})
SELU = type("SELU", (_simple("selu"),), {})
Silu = type("Silu", (_simple("silu"),), {})
Swish = type("Swish", (_simple("swish"),), {})
Mish = type("Mish", (_simple("mish"),), {})
Sigmoid = type("Sigmoid", (_simple("sigmoid"),), {})
Hardsigmoid = type("Hardsigmoid", (_simple("hardsigmoid"),), {})
Hardswish = type("Hardswish", (_simple("hardswish"),), {})
Softsign = type("Softsign", (_simple("softsign"),), {})
Tanhshrink = type("Tanhshrink", (_simple("tanhshrink"),), {})
Tanh = type("Tanh", (_simple("tanh"),), {})


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, negative_slope=float(self.negative_slope))


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, alpha=float(self.alpha))


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, alpha=float(self.alpha))


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=bool(self.approximate))


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, min=float(self.min), max=float(self.max))


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, beta=float(self.beta),
                          threshold=float(self.threshold))


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, threshold=float(self.threshold))


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, threshold=float(self.threshold))


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=int(self.axis))


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=int(self.axis))


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, axis=int(self.axis))
