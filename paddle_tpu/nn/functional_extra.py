"""Functional tail (reference: python/paddle/nn/functional/* names
without a previous counterpart). Mostly thin functional forms of the
layer classes in extra_layers.py; real new math: rnnt_loss (transducer
DP as nested lax.scans), gumbel_softmax, sigmoid_focal_loss, dice_loss,
fractional max-pooling, class_center_sample.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core import rng as _rng
from ..core.dispatch import def_op
from ..core.enforce import enforce
from ..tensor import Tensor, to_tensor

__all__ = [
    "avg_pool1d", "max_pool1d", "adaptive_avg_pool1d",
    "adaptive_max_pool1d", "adaptive_avg_pool3d", "adaptive_max_pool3d",
    "max_unpool1d", "max_unpool2d", "conv1d_transpose", "conv3d_transpose",
    "alpha_dropout", "dropout3d", "bilinear", "zeropad2d", "upsample",
    "pairwise_distance", "pdist", "local_response_norm",
    "cosine_embedding_loss", "gaussian_nll_loss", "hinge_embedding_loss",
    "multi_label_soft_margin_loss", "multi_margin_loss",
    "poisson_nll_loss", "soft_margin_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "margin_ranking_loss",
    "sigmoid_focal_loss", "dice_loss", "npair_loss", "gumbel_softmax",
    "hsigmoid_loss", "rnnt_loss", "fractional_max_pool2d",
    "fractional_max_pool3d", "class_center_sample",
    "relu_", "tanh_", "softmax_", "elu_", "hardtanh_", "leaky_relu_",
    "thresholded_relu_",
    "max_pool3d", "avg_pool3d", "max_unpool3d", "rrelu", "log_sigmoid",
    "swiglu", "margin_cross_entropy",
]

from ..ops.pool3d import avg_pool3d, max_pool3d, max_unpool3d  # noqa: E402,F401
from ..ops.extra import log_sigmoid, rrelu  # noqa: E402,F401
from ..incubate.nn.functional import swiglu  # noqa: E402,F401




def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace/CosFace-family margin softmax loss (reference:
    python/paddle/nn/functional/common.py margin_cross_entropy over the
    margin_cross_entropy kernel): logits are COSINES; the target class
    logit becomes cos(m1*theta + m2) - m3, everything scaled by s.
    The model-parallel form (group=) is served by
    mp_layers.ParallelCrossEntropy over vocab-sharded logits."""
    enforce(group is None or group is False,
            "margin_cross_entropy(group=...) model-parallel form: use "
            "paddle_tpu.distributed.fleet.meta_parallel.ParallelCross"
            "Entropy on the vocab-sharded logits instead")
    return _margin_ce(logits, label, float(margin1), float(margin2),
                      float(margin3), float(scale), bool(return_softmax),
                      reduction)


@def_op("margin_cross_entropy")
def _margin_ce(logits, label, m1, m2, m3, s, return_softmax, reduction):
    lg = jnp.clip(logits.astype(jnp.float32), -1.0, 1.0)
    N, C = lg.shape
    lab = label.reshape(-1).astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, C, dtype=jnp.float32)
    theta = jnp.arccos(lg)
    target = jnp.cos(m1 * theta + m2) - m3
    adj = jnp.where(onehot > 0, target, lg) * s
    logp = jax.nn.log_softmax(adj, axis=-1)
    loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
    if reduction == "mean":
        loss = jnp.mean(loss)
    elif reduction == "sum":
        loss = jnp.sum(loss)
    if return_softmax:
        return loss, jnp.exp(logp).astype(logits.dtype)
    return loss


# ---------------------------------------------------------------------------
# delegations to the layer implementations
# ---------------------------------------------------------------------------
def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               **kw):
    from .extra_layers import AvgPool1D

    return AvgPool1D(kernel_size, stride, padding, ceil_mode)(x)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               **kw):
    from .extra_layers import MaxPool1D

    return MaxPool1D(kernel_size, stride, padding, ceil_mode)(x)


def adaptive_avg_pool1d(x, output_size, name=None):
    from .extra_layers import AdaptiveAvgPool1D

    return AdaptiveAvgPool1D(output_size)(x)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    from .extra_layers import AdaptiveMaxPool1D

    enforce(not return_mask, "return_mask is not supported here")
    return AdaptiveMaxPool1D(output_size)(x)


def adaptive_avg_pool3d(x, output_size, name=None):
    from .extra_layers import AdaptiveAvgPool3D

    return AdaptiveAvgPool3D(output_size)(x)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    from .extra_layers import AdaptiveMaxPool3D

    enforce(not return_mask, "return_mask is not supported here")
    return AdaptiveMaxPool3D(output_size)(x)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, name=None):
    from .extra_layers import MaxUnPool1D

    return MaxUnPool1D(kernel_size, stride, padding)(x, indices,
                                                     output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, name=None):
    from ..ops.extra import max_unpool2d as _unpool

    return _unpool(x, indices, kernel_size, stride, padding, output_size)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, name=None):
    from .extra_layers import _conv_transpose_nd

    enforce(groups == 1, "conv1d_transpose here supports groups=1")
    return _conv_transpose_nd(x, weight, bias, stride, padding, 1,
                              dilation, output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, name=None):
    from .extra_layers import _conv_transpose_nd

    enforce(groups == 1, "conv3d_transpose here supports groups=1")
    return _conv_transpose_nd(x, weight, bias, stride, padding, 3,
                              dilation, output_padding)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or not p:
        return x
    from .extra_layers import _alpha_dropout

    return _alpha_dropout(x, float(p), _rng.get_key())


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    if not training or not p:
        return x
    from .extra_layers import _channel_dropout

    return _channel_dropout(x, float(p), _rng.get_key())


def bilinear(x1, x2, weight, bias=None, name=None):
    from .extra_layers import _bilinear

    return _bilinear(x1, x2, weight, bias)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from .functional import pad as _pad

    p = [int(padding)] * 4 if np.isscalar(padding) \
        else [int(v) for v in padding]
    return _pad(x, p, mode="constant", value=0.0,
                data_format=data_format)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, **kw):
    from .functional import interpolate

    return interpolate(x, size=size, scale_factor=scale_factor,
                       mode=mode, align_corners=align_corners)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False,
                      name=None):
    from .extra_layers import _pairwise_distance

    return _pairwise_distance(x, y, float(p), float(epsilon),
                              bool(keepdim))


@def_op("pdist")
def pdist(x, p=2.0):
    """Condensed pairwise distances of rows (reference: functional
    distance.py pdist)."""
    n = x.shape[0]
    d = jnp.sum(jnp.abs(x[:, None] - x[None, :]) ** p, axis=-1) \
        ** (1.0 / p)
    iu = jnp.triu_indices(n, k=1)
    return d[iu]


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    from . import LocalResponseNorm

    return LocalResponseNorm(size, alpha, beta, k)(x)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    from .extra_layers import CosineEmbeddingLoss

    return CosineEmbeddingLoss(margin, reduction)(input1, input2, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    from .extra_layers import GaussianNLLLoss

    return GaussianNLLLoss(full, epsilon, reduction)(input, label,
                                                     variance)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    from .extra_layers import HingeEmbeddingLoss

    return HingeEmbeddingLoss(margin, reduction)(input, label)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    from .extra_layers import MultiLabelSoftMarginLoss

    return MultiLabelSoftMarginLoss(weight, reduction)(input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    from .extra_layers import MultiMarginLoss

    return MultiMarginLoss(p, margin, weight, reduction)(input, label)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    from .extra_layers import PoissonNLLLoss

    return PoissonNLLLoss(log_input, full, epsilon, reduction)(input,
                                                               label)


def soft_margin_loss(input, label, reduction="mean", name=None):
    from .extra_layers import SoftMarginLoss

    return SoftMarginLoss(reduction)(input, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    from .extra_layers import TripletMarginLoss

    return TripletMarginLoss(margin, p, epsilon, swap, reduction)(
        input, positive, negative)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    from .extra_layers import TripletMarginWithDistanceLoss

    return TripletMarginWithDistanceLoss(distance_function, margin, swap,
                                         reduction)(input, positive,
                                                    negative)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Functional hsigmoid over caller-supplied parameters (reference:
    functional/loss.py hsigmoid_loss; default complete-binary-tree
    paths, custom path tables unsupported)."""
    from .extra_layers import _build_tree_paths, _hsigmoid_loss

    enforce(path_table is None and path_code is None,
            "custom path tables are not supported here")
    codes, signs, mask = _build_tree_paths(int(num_classes))
    return _hsigmoid_loss(input, label, weight, bias, codes, signs, mask)


# ---------------------------------------------------------------------------
# new math
# ---------------------------------------------------------------------------
@def_op("margin_ranking_loss")
def margin_ranking_loss(input, other, label, margin=0.0,
                        reduction="mean"):
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@def_op("sigmoid_focal_loss")
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum"):
    """(reference: functional/loss.py sigmoid_focal_loss — RetinaNet
    focal loss over logits)."""
    p = jax.nn.sigmoid(logit)
    ce = -(label * jax.nn.log_sigmoid(logit)
           + (1 - label) * jax.nn.log_sigmoid(-logit))
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@def_op("dice_loss")
def dice_loss(input, label, epsilon=1e-5):
    """(reference: functional/loss.py dice_loss): input [..., C]
    probabilities, integer label [..., 1]."""
    C = input.shape[-1]
    lab = jax.nn.one_hot(label[..., 0], C, dtype=input.dtype)
    red = tuple(range(1, input.ndim))
    inter = jnp.sum(input * lab, axis=red)
    union = jnp.sum(input, axis=red) + jnp.sum(lab, axis=red)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


@def_op("npair_loss")
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """(reference: functional/loss.py npair_loss)."""
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), 1))
                    + jnp.mean(jnp.sum(jnp.square(positive), 1))) * 0.25
    sim = anchor @ positive.T                       # [B, B]
    lab = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    lab = lab / jnp.sum(lab, axis=1, keepdims=True)
    xent = -jnp.sum(jax.nn.log_softmax(sim, axis=1) * lab, axis=1)
    return jnp.mean(xent) + reg


@def_op("gumbel_softmax_op", differentiable=True)
def _gumbel_softmax(x, key, temperature, hard, axis):
    g = -jnp.log(-jnp.log(
        jax.random.uniform(key, x.shape, minval=1e-20, maxval=1.0)))
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.put_along_axis(jnp.zeros_like(y), idx, 1.0,
                                    axis=axis, inplace=False)
        # straight-through: hard forward, soft backward
        y = lax.stop_gradient(onehot - y) + y
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    return _gumbel_softmax(x, _rng.get_key(), float(temperature),
                           bool(hard), int(axis))


@def_op("rnnt_loss_op")
def _rnnt_loss(logits, labels, input_lengths, label_lengths, blank):
    """RNN-Transducer loss (reference: warprnnt_op): forward-alpha DP
    over the [T, U+1] lattice, scan over t with an inner scan over u —
    all in log space, differentiable through both scans.

    logits: [B, T, U+1, V] log-probs (log_softmax applied here),
    labels: [B, U]."""
    B, T, U1, V = logits.shape
    U = U1 - 1
    lp = jax.nn.log_softmax(logits, axis=-1)
    # blank/emit lattices
    lp_blank = lp[..., blank]                       # [B, T, U+1]
    emit_idx = jnp.concatenate(
        [labels, jnp.full((B, 1), blank, labels.dtype)], 1)  # pad col
    lp_emit = jnp.take_along_axis(
        lp, emit_idx[:, None, :, None], axis=3)[..., 0]      # [B,T,U+1]
    NEG = -1e30

    def row_step(carry_row, t):
        # carry_row: alpha[t-1, :] for all b -> [B, U+1]
        prev = carry_row

        def inner(carry_u, u):
            # alpha[t, u] = logaddexp(prev[u] + blank(t-1, u),
            #                         alpha[t, u-1] + emit(t, u-1))
            a_left = carry_u                         # alpha[t, u-1]
            from_top = jnp.where(
                t > 0, prev[:, u] + lp_blank[:, jnp.maximum(t - 1, 0), u],
                jnp.where(u == 0, 0.0, NEG))
            from_left = jnp.where(
                u > 0,
                a_left + lp_emit[:, t, jnp.maximum(u - 1, 0)], NEG)
            m = jnp.maximum(from_top, from_left)
            safe = jnp.where(m <= NEG / 2, 0.0, m)
            val = safe + jnp.log(
                jnp.exp(jnp.where(m <= NEG / 2, 0.0, from_top - safe))
                + jnp.exp(jnp.where(m <= NEG / 2, NEG, from_left - safe)
                          ))
            val = jnp.where(m <= NEG / 2, NEG, val)
            # t=0, u=0 -> 0 (log 1)
            val = jnp.where((t == 0) & (u == 0), 0.0, val)
            return val, val

        _, row = lax.scan(inner, jnp.full((B,), NEG), jnp.arange(U1))
        row = row.T                                  # [B, U+1]
        return row, row

    _, alphas = lax.scan(row_step, jnp.full((B, U1), NEG),
                         jnp.arange(T))              # [T, B, U+1]
    alphas = alphas.transpose(1, 0, 2)               # [B, T, U+1]
    t_last = input_lengths - 1
    u_last = label_lengths
    a_last = alphas[jnp.arange(B), t_last, u_last]
    final_blank = lp_blank[jnp.arange(B), t_last, u_last]
    return -(a_last + final_blank)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """(reference: functional/loss.py rnnt_loss over warprnnt).
    FastEmit regularization is not implemented — a nonzero
    fastemit_lambda raises rather than silently diverging."""
    enforce(not fastemit_lambda,
            "fastemit_lambda is not supported here (pass 0.0)")
    loss = _rnnt_loss(input, label, input_lengths, label_lengths,
                      int(blank))
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Fractional max pooling (reference: functional/pooling.py
    fractional_max_pool2d; Graham 2014 pseudo-random bin edges from a
    single u). Disjoint bins only — overlapping kernel_size raises."""
    enforce(kernel_size is None,
            "explicit kernel_size (overlapping windows) unsupported")
    enforce(not return_mask, "return_mask is not supported here")
    # α-based fractional bins degrade gracefully to adaptive max bins
    # when u is None (paddle draws u ~ U(0,1) then derives edges)
    if random_u is None:
        random_u = float(jax.random.uniform(_rng.get_key(), ()))
    out_hw = ((output_size, output_size) if np.isscalar(output_size)
              else tuple(output_size))
    return _fractional_pool(x, out_hw, float(random_u), 2)


def fractional_max_pool3d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    enforce(kernel_size is None,
            "explicit kernel_size (overlapping windows) unsupported")
    enforce(not return_mask, "return_mask is not supported here")
    if random_u is None:
        random_u = float(jax.random.uniform(_rng.get_key(), ()))
    out = ((output_size,) * 3 if np.isscalar(output_size)
           else tuple(output_size))
    return _fractional_pool(x, out, float(random_u), 3)


@def_op("fractional_pool")
def _fractional_pool(x, out_sizes, u, nd):
    spatial0 = x.ndim - nd
    out = x
    for i, osz in enumerate(out_sizes):
        ax = spatial0 + i
        isz = out.shape[ax]
        alpha = isz / osz
        # Graham's pseudo-random increments: ceil(alpha*(j+u)) edges
        edges = [int(np.ceil(alpha * (j + u))) - int(np.ceil(alpha * u))
                 for j in range(osz + 1)]
        edges[-1] = isz
        slabs = []
        for j in range(osz):
            lo = min(edges[j], isz - 1)
            hi = max(min(edges[j + 1], isz), lo + 1)
            sl = lax.slice_in_dim(out, lo, hi, axis=ax)
            slabs.append(jnp.max(sl, axis=ax, keepdims=True))
        out = jnp.concatenate(slabs, axis=ax)
    return out


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference: functional/common.py
    class_center_sample for PartialFC). Host-side: the sampled set is
    data-dependent."""
    lab = np.asarray(label._value if isinstance(label, Tensor)
                     else label)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        key = _rng.get_key()
        perm = np.asarray(jax.random.permutation(key, len(rest)))
        sampled = np.concatenate(
            [pos, rest[perm[: num_samples - len(pos)]]])
    sampled = np.sort(sampled)
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return to_tensor(remap[lab]), to_tensor(sampled)


# ---------------------------------------------------------------------------
# inplace variants: value-swap on the tensor (immutable arrays under the
# hood — the reference's foo_ ops mutate storage; here the Tensor's
# _value is replaced and the result is returned, matching user-visible
# semantics for leaf tensors outside autograd)
# ---------------------------------------------------------------------------
def _inplace(fn):
    from ..tensor import inplace_swap

    def wrapper(x, *a, **kw):
        return inplace_swap(x, fn(x, *a, **kw))
    return wrapper


def relu_(x, name=None):
    from .functional import relu

    return _inplace(relu)(x)


def tanh_(x, name=None):
    from .functional import tanh

    return _inplace(tanh)(x)


def softmax_(x, axis=-1, name=None):
    from .functional import softmax

    return _inplace(softmax)(x, axis=axis)


def elu_(x, alpha=1.0, name=None):
    from .functional import elu

    return _inplace(elu)(x, alpha=alpha)


def hardtanh_(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    from .functional import hardtanh

    return _inplace(hardtanh)(x, min, max)


def leaky_relu_(x, negative_slope=0.01, name=None):
    from .functional import leaky_relu

    return _inplace(leaky_relu)(x, negative_slope)


def thresholded_relu_(x, threshold=1.0, name=None):
    from ..ops.extra import thresholded_relu

    return _inplace(thresholded_relu)(x, threshold)
