"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).

MultiHeadAttention keeps paddle's [batch, seq, hidden] layout and its
Cache/StaticCache API for incremental decode; attention math routes
through the flash-attention op (Pallas kernel on TPU).
"""
from __future__ import annotations

import collections
from typing import Optional

from .. import ops
from ..ops import manipulation as MP
from ..ops import math as M
from . import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layer import Layer
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        B, S = x.shape[0], x.shape[1]
        return MP.reshape(x, shape=(B, S, self.num_heads, self.head_dim))

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        B = key.shape[0]
        k = ops.zeros((B, 0, self.num_heads, self.head_dim), dtype=str(key.dtype))
        v = ops.zeros((B, 0, self.num_heads, self.head_dim), dtype=str(key.dtype))
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                k = MP.concat([cache.k, k], axis=1)
                v = MP.concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)

        causal = attn_mask is None and cache is None and query.shape[1] > 1
        if attn_mask is not None:
            from ..ops import nn_ops as N

            out = N.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=False)
        else:
            out, _ = F.flash_attention(q, k, v, causal=causal,
                                       training=self.training,
                                       dropout=self.dropout)
        B, S = out.shape[0], out.shape[1]
        out = MP.reshape(out, shape=(B, S, self.embed_dim))
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [
            copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, sc = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, stc = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (sc, stc))

    def gen_cache(self, memory):
        return (self.self_attn.gen_cache(memory),
                self.cross_attn.gen_cache(memory,
                                          type=MultiHeadAttention.StaticCache))


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [
            copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask,
                                  cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        return [layer.gen_cache(memory) for layer in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc = TransformerEncoderLayer(d_model, nhead, dim_feedforward,
                                          dropout, activation, attn_dropout,
                                          act_dropout, normalize_before)
            self.encoder = TransformerEncoder(
                enc, num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec = TransformerDecoderLayer(d_model, nhead, dim_feedforward,
                                          dropout, activation, attn_dropout,
                                          act_dropout, normalize_before)
            self.decoder = TransformerDecoder(
                dec, num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)
