"""Layer: the module base class.

(reference: python/paddle/nn/layer/layers.py ``Layer`` — parameter/sublayer
registration via __setattr__, state_dict, hooks, train/eval. The TPU build
keeps the identical surface; parameters wrap jax.Arrays and all state is
functional under the hood so a whole Layer forward traces cleanly into XLA.)
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.dtype import convert_dtype, get_default_dtype
from ..tensor import Parameter, Tensor
from . import initializer as I

__all__ = ["Layer"]


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks: Dict[int, Callable]):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) if dtype is not None else get_default_dtype()
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            layers.pop(name, None) if layers else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            params.pop(name, None) if params else None
            self.__dict__.pop(name, None)
        else:
            if params and name in params:
                if value is None:
                    params.pop(name)
                else:
                    raise TypeError(f"cannot assign non-Parameter to parameter {name}")
            elif layers and name in layers:
                if value is None:
                    layers.pop(name)
                else:
                    layers[name] = value
                    return
            elif buffers is not None and name in buffers:
                buffers[name] = value
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters.pop(name, None)
        else:
            self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name: str, tensor: Tensor, persistable: bool = True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        elif tensor is not None:
            tensor.persistable = True

    def create_parameter(self, shape, attr=None, dtype=None, is_bias: bool = False,
                         default_initializer=None) -> Parameter:
        """Analog of Layer.create_parameter (LayerHelper path in reference)."""
        from ..framework.param_attr import ParamAttr

        dtype = convert_dtype(dtype) if dtype is not None else self._dtype
        init = default_initializer
        name = None
        trainable = True
        if isinstance(attr, ParamAttr):
            if attr.initializer is not None:
                init = attr.initializer
            name = attr.name
            trainable = attr.trainable
        elif attr is False:
            return None
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        from ..framework.lazy_init import LazySpec, in_lazy_mode

        if in_lazy_mode():
            # LazyGuard: no storage — ParallelEngine materializes each
            # param directly at its sharding (framework/lazy_init.py)
            value = LazySpec(tuple(shape), dtype, init)
        else:
            value = init(tuple(shape), dtype)
        p = Parameter(value, name=name, trainable=trainable)
        return p

    def create_tensor(self, name=None, dtype=None):
        dtype = convert_dtype(dtype) if dtype is not None else self._dtype
        return Tensor(jnp.zeros((), dtype), name=name)

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (name + ("." if name else "") + pname, p)

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (name + ("." if name else "") + bname, b)

    def _traverse(self, prefix: str, include_sublayers: bool
                  ) -> Iterator[Tuple[str, "Layer"]]:
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = prefix + ("." if prefix else "") + name
                yield from sub._traverse(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, sub in self.named_children():
            yield sub

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        out = []
        for name, layer in self._traverse("", True):
            if layer is self and not include_self:
                continue
            out.append(layer)
        return out

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        for name, layer in self._traverse(prefix, True):
            if layer is self and not include_self:
                continue
            yield name, layer

    def apply(self, fn: Callable) -> "Layer":
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ------------------------------------------------------------------
    # modes / dtype moves
    # ------------------------------------------------------------------
    def train(self) -> "Layer":
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self) -> "Layer":
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None) -> "Layer":
        if dtype is not None:
            dtype = convert_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(dtype)
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b._value.dtype, jnp.floating):
                    b._value = b._value.astype(dtype)
            for layer in self.sublayers(include_self=True):
                layer._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True
                   ) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix,
                                             include_sublayers):
            dest[name] = p
        for name, layer in self._traverse(structured_name_prefix,
                                          include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    dest[name + ("." if name else "") + bname] = b
        return dest

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                src = state_dict[name]
                value = src._value if isinstance(src, Tensor) else jnp.asarray(src)
                if tuple(value.shape) != tuple(target._value.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: {value.shape} vs "
                        f"{target._value.shape}")
                target._value = value.astype(target._value.dtype)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # call & hooks
    # ------------------------------------------------------------------
    def register_forward_pre_hook(self, hook: Callable) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook: Callable) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
