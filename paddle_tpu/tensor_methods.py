"""Monkey-patch math/manipulation methods onto Tensor.

The reference binds Tensor methods in C++ (pybind eager_method.cc) and
monkey-patches the rest from python (python/paddle/base/dygraph/math_op_patch.py).
We use the same late-binding strategy to break the Tensor <-> ops cycle.
"""
from __future__ import annotations

from .core.dtype import convert_dtype
from .tensor import Tensor
from .ops import creation, manipulation, math, nn_ops


def _patch():
    T = Tensor

    # -- arithmetic dunders --------------------------------------------
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(s, o)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(o, s) if isinstance(o, Tensor) \
        else math.scale(math.subtract(s, o), scale=-1.0)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(s, o)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(o, s) if isinstance(o, Tensor) \
        else math.multiply(math.reciprocal(s), o)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__mod__ = lambda s, o: math.remainder(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(creation.full_like(s, o), s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__matmul__ = lambda s, o: math.matmul(s, o)
    T.__invert__ = lambda s: math.logical_not(s)

    # -- comparisons (assigned post-class-creation so __hash__ survives)
    T.__eq__ = lambda s, o: math.equal(s, o)
    T.__ne__ = lambda s, o: math.not_equal(s, o)
    T.__lt__ = lambda s, o: math.less_than(s, o)
    T.__le__ = lambda s, o: math.less_equal(s, o)
    T.__gt__ = lambda s, o: math.greater_than(s, o)
    T.__ge__ = lambda s, o: math.greater_equal(s, o)

    # -- indexing ------------------------------------------------------
    T.__getitem__ = lambda s, item: manipulation.getitem(s, item)

    # -- named methods: ops functions double as methods (self = 1st arg)
    for name in [
        "add", "subtract", "multiply", "divide", "pow", "maximum", "minimum",
        "remainder", "floor_divide",
        "exp", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "abs",
        "sign", "reciprocal", "square", "sin", "cos", "tan", "tanh", "erf",
        "floor", "ceil", "round", "trunc", "clip", "scale", "neg", "lerp",
        "sum", "mean", "max", "min", "prod", "logsumexp", "std", "var",
        "all", "any", "cumsum", "cumprod",
        "matmul", "dot", "t", "norm", "bmm",
        "equal", "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal", "logical_and", "logical_or", "logical_not",
        "isnan", "isinf", "isfinite", "isclose", "allclose",
        "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
    ]:
        setattr(T, name, getattr(math, name))

    for name in [
        "reshape", "transpose", "squeeze", "unsqueeze", "expand",
        "expand_as", "broadcast_to", "tile", "roll", "flip",
        "gather", "gather_nd", "index_select", "scatter", "split", "chunk",
        "unbind", "tril", "triu", "take_along_axis", "put_along_axis",
        "masked_fill", "repeat_interleave", "numel", "unstack",
    ]:
        setattr(T, name, getattr(manipulation, name))

    T.flatten = lambda s, start_axis=0, stop_axis=-1: manipulation.flatten(
        s, start_axis=start_axis, stop_axis=stop_axis)
    T.astype = lambda s, dtype: manipulation.cast(s, dtype=convert_dtype(dtype))
    T.cast = T.astype
    T.dim = lambda s: s.ndim
    T.rank = lambda s: s.ndim
    T.zeros_like = lambda s: creation.zeros_like(s)
    T.ones_like = lambda s: creation.ones_like(s)
    T.softmax = lambda s, axis=-1: nn_ops.softmax(s, axis=axis)
    T.mm = lambda s, o: math.matmul(s, o)
    T.T = property(lambda s: manipulation.transpose(
        s, perm=tuple(range(s.ndim))[::-1]))

    # -- in-place variants (functional under the hood) -----------------
    from .tensor import inplace_swap

    def _make_inplace(fn):
        def method(self, *args, **kwargs):
            return inplace_swap(self, fn(self, *args, **kwargs))
        return method

    for name, fn in [
        ("add_", math.add), ("subtract_", math.subtract),
        ("multiply_", math.multiply), ("divide_", math.divide),
        ("scale_", math.scale), ("clip_", math.clip),
        ("exp_", math.exp), ("sqrt_", math.sqrt),
        ("reshape_", manipulation.reshape), ("squeeze_", manipulation.squeeze),
        ("unsqueeze_", manipulation.unsqueeze),
    ]:
        setattr(T, name, _make_inplace(fn))


_patch()


def patch_namespace_methods(ns):
    """Bind remaining reference Tensor methods from the top-level
    namespace (reference: python/paddle/tensor/__init__.py
    tensor_method_func — there the pybind monkey-patch does the same
    job). Called at the end of package __init__, when the full function
    surface exists; only names not already bound are added, each
    delegating to the namespace function with the tensor as first arg.
    """
    from .tensor import Tensor as T

    probe = T.__dict__  # only skip names bound directly on Tensor

    def bind(name, fn):
        def method(self, *args, **kwargs):
            return fn(self, *args, **kwargs)
        method.__name__ = name
        setattr(T, name, method)

    # only names ABSENT from the reference method list below (extras
    # this framework also exposes as methods)
    names = [
        "crop", "increment", "logspace", "strided_slice", "dist",
        "equal_all", "is_empty", "clip_by_norm", "multiplex",
        "shard_index", "stanh", "i0e", "i1", "i1e",
    ]
    _REFERENCE_METHOD_NAMES = """
abs abs_ acos acos_ acosh acosh_ add add_ add_n addmm addmm_ all
allclose amax amin angle any argmax argmin argsort as_complex
as_real as_strided asin asin_ asinh asinh_ atan atan2 atan_ atanh
atanh_ atleast_1d atleast_2d atleast_3d bincount bitwise_and
bitwise_and_ bitwise_left_shift bitwise_left_shift_ bitwise_not
bitwise_not_ bitwise_or bitwise_or_ bitwise_right_shift
bitwise_right_shift_ bitwise_xor bitwise_xor_ bmm broadcast_shape
broadcast_tensors broadcast_to bucketize cast cast_ cauchy_ cdist
ceil ceil_ cholesky cholesky_solve chunk clip clip_ concat cond conj
copysign copysign_ corrcoef cos cos_ cosh cosh_ count_nonzero cov
create_parameter create_tensor cross cummax cummin cumprod cumprod_
cumsum cumsum_ cumulative_trapezoid deg2rad diag diag_embed diagflat
diagonal diagonal_scatter diff digamma digamma_ dist divide divide_
dot dsplit eig eigvals eigvalsh equal equal_ equal_all erf erfinv
erfinv_ exp exp_ expand expand_as expm1 exponential_ flatten
flatten_ flip floor floor_ floor_divide floor_divide_ floor_mod
floor_mod_ fmax fmin frac frac_ frexp gammainc gammainc_ gammaincc
gammaincc_ gammaln gammaln_ gather gather_nd gcd gcd_ geometric_
greater_equal greater_equal_ greater_than greater_than_ heaviside
histogram histogramdd householder_product hsplit hypot hypot_ i0 i0_
i0e i1 i1e imag increment index_add index_fill index_fill_ index_put
index_put_ index_sample index_select inner inverse is_complex
is_empty is_floating_point is_integer is_tensor isclose isfinite
isinf isnan istft kron kthvalue lcm lcm_ ldexp ldexp_ lerp lerp_
less_equal less_equal_ less_than less_than_ lgamma lgamma_ log log10
log10_ log1p log1p_ log2 log2_ log_ logaddexp logcumsumexp
logical_and logical_and_ logical_not logical_not_ logical_or
logical_or_ logical_xor logical_xor_ logit logit_ logsumexp lstsq lu
lu_unpack masked_fill masked_fill_ masked_scatter masked_scatter_
masked_select matmul matrix_power max maximum mean median min
minimum mm mod mod_ moveaxis multi_dot multigammaln multigammaln_
multinomial multiplex multiply multiply_ mv nan_to_num nan_to_num_
nanmean nanmedian nanquantile nansum neg neg_ nextafter nonzero norm
normal_ not_equal not_equal_ numel outer pca_lowrank pinv polar
polygamma polygamma_ pow pow_ prod put_along_axis put_along_axis_ qr
quantile rad2deg rank real reciprocal reciprocal_ remainder
remainder_ renorm renorm_ repeat_interleave reshape reshape_ reverse
roll rot90 round round_ rsqrt rsqrt_ scale scale_ scatter scatter_
scatter_nd scatter_nd_add select_scatter sgn shape shard_index
sigmoid sigmoid_ sign signbit sin sin_ sinh sinh_ slice
slice_scatter solve sort split sqrt sqrt_ square squeeze squeeze_
stack stanh std stft strided_slice subtract subtract_ sum t t_ take
take_along_axis tan tan_ tanh tanh_ tensor_split tensordot tile
top_p_sampling topk trace transpose transpose_ trapezoid
triangular_solve tril tril_ triu triu_ trunc trunc_ unbind unflatten
unfold uniform_ unique unique_consecutive unsqueeze unsqueeze_
unstack vander var view view_as vsplit where where_
""".split()
    for name in names + _REFERENCE_METHOD_NAMES:
        if name in probe or hasattr(T, name):
            continue
        fn = ns.get(name)
        if callable(fn):
            bind(name, fn)
    sig = ns.get("signal")
    if sig is not None:
        for name in ("stft", "istft"):
            if name not in probe:
                bind(name, getattr(sig, name))
    from .ops.api_tail import tensor_unfold as _tu

    if "unfold" not in probe:
        bind("unfold", _tu)

