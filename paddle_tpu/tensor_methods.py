"""Monkey-patch math/manipulation methods onto Tensor.

The reference binds Tensor methods in C++ (pybind eager_method.cc) and
monkey-patches the rest from python (python/paddle/base/dygraph/math_op_patch.py).
We use the same late-binding strategy to break the Tensor <-> ops cycle.
"""
from __future__ import annotations

from .core.dtype import convert_dtype
from .tensor import Tensor
from .ops import creation, manipulation, math, nn_ops


def _patch():
    T = Tensor

    # -- arithmetic dunders --------------------------------------------
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(s, o)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(o, s) if isinstance(o, Tensor) \
        else math.scale(math.subtract(s, o), scale=-1.0)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(s, o)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(o, s) if isinstance(o, Tensor) \
        else math.multiply(math.reciprocal(s), o)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__mod__ = lambda s, o: math.remainder(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(creation.full_like(s, o), s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__matmul__ = lambda s, o: math.matmul(s, o)
    T.__invert__ = lambda s: math.logical_not(s)

    # -- comparisons (assigned post-class-creation so __hash__ survives)
    T.__eq__ = lambda s, o: math.equal(s, o)
    T.__ne__ = lambda s, o: math.not_equal(s, o)
    T.__lt__ = lambda s, o: math.less_than(s, o)
    T.__le__ = lambda s, o: math.less_equal(s, o)
    T.__gt__ = lambda s, o: math.greater_than(s, o)
    T.__ge__ = lambda s, o: math.greater_equal(s, o)

    # -- indexing ------------------------------------------------------
    T.__getitem__ = lambda s, item: manipulation.getitem(s, item)

    # -- named methods: ops functions double as methods (self = 1st arg)
    for name in [
        "add", "subtract", "multiply", "divide", "pow", "maximum", "minimum",
        "remainder", "floor_divide",
        "exp", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "abs",
        "sign", "reciprocal", "square", "sin", "cos", "tan", "tanh", "erf",
        "floor", "ceil", "round", "trunc", "clip", "scale", "neg", "lerp",
        "sum", "mean", "max", "min", "prod", "logsumexp", "std", "var",
        "all", "any", "cumsum", "cumprod",
        "matmul", "dot", "t", "norm", "bmm",
        "equal", "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal", "logical_and", "logical_or", "logical_not",
        "isnan", "isinf", "isfinite", "isclose", "allclose",
        "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
    ]:
        setattr(T, name, getattr(math, name))

    for name in [
        "reshape", "transpose", "squeeze", "unsqueeze", "expand",
        "expand_as", "broadcast_to", "tile", "roll", "flip",
        "gather", "gather_nd", "index_select", "scatter", "split", "chunk",
        "unbind", "tril", "triu", "take_along_axis", "put_along_axis",
        "masked_fill", "repeat_interleave", "numel", "unstack",
    ]:
        setattr(T, name, getattr(manipulation, name))

    T.flatten = lambda s, start_axis=0, stop_axis=-1: manipulation.flatten(
        s, start_axis=start_axis, stop_axis=stop_axis)
    T.astype = lambda s, dtype: manipulation.cast(s, dtype=convert_dtype(dtype))
    T.cast = T.astype
    T.dim = lambda s: s.ndim
    T.rank = lambda s: s.ndim
    T.zeros_like = lambda s: creation.zeros_like(s)
    T.ones_like = lambda s: creation.ones_like(s)
    T.softmax = lambda s, axis=-1: nn_ops.softmax(s, axis=axis)
    T.mm = lambda s, o: math.matmul(s, o)
    T.T = property(lambda s: manipulation.transpose(
        s, perm=tuple(range(s.ndim))[::-1]))

    # -- in-place variants (functional under the hood) -----------------
    from .tensor import inplace_swap

    def _make_inplace(fn):
        def method(self, *args, **kwargs):
            return inplace_swap(self, fn(self, *args, **kwargs))
        return method

    for name, fn in [
        ("add_", math.add), ("subtract_", math.subtract),
        ("multiply_", math.multiply), ("divide_", math.divide),
        ("scale_", math.scale), ("clip_", math.clip),
        ("exp_", math.exp), ("sqrt_", math.sqrt),
        ("reshape_", manipulation.reshape), ("squeeze_", manipulation.squeeze),
        ("unsqueeze_", manipulation.unsqueeze),
    ]:
        setattr(T, name, _make_inplace(fn))


_patch()
