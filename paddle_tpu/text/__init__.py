"""paddle.text analog (reference: python/paddle/text/ —
viterbi_decode.py over the phi viterbi_decode kernel; datasets are IO
helpers outside the compute scope)."""
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = ["viterbi_decode", "ViterbiDecoder"]
