"""Viterbi decoding for linear-chain CRFs (reference: python/paddle/
text/viterbi_decode.py over the phi viterbi_decode kernel,
paddle/phi/kernels/viterbi_decode_kernel.h).

TPU design: the max-product recursion is one ``lax.scan`` over time
(compiled once for any length), the argmax backtrace a second reversed
scan — no per-step host dispatch, static shapes throughout; padded
steps beyond each sequence's length carry the state through unchanged.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op
from ..core.enforce import enforce

__all__ = ["viterbi_decode", "ViterbiDecoder"]


@def_op("viterbi_decode", differentiable=False)
def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True):
    """Highest-scoring tag path per sequence.

    potentials [B, T, N] unary emissions, transition_params [N, N],
    lengths [B]. Returns (scores [B], paths [B, T] int64-compatible).
    """
    enforce(potentials.ndim == 3,
            lambda: f"potentials must be [B,T,N], got rank {potentials.ndim}")
    B, T, N = potentials.shape
    trans = transition_params.astype(potentials.dtype)
    lengths = lengths.astype(jnp.int32)

    if include_bos_eos_tag:
        # last tag = BOS, second-to-last = STOP (reference convention):
        # alpha starts from the BOS row; STOP column is added at each
        # sequence's end.
        alpha0 = potentials[:, 0] + trans[-1][None, :]
    else:
        alpha0 = potentials[:, 0]

    def fwd(carry, t):
        alpha = carry                                   # [B, N]
        emit = potentials[:, t]                         # [B, N]
        # score[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, j]
        score = alpha[:, :, None] + trans[None]         # [B, N, N]
        best = jnp.max(score, axis=1) + emit            # [B, N]
        back = jnp.argmax(score, axis=1)                # [B, N]
        live = (t < lengths)[:, None]
        return jnp.where(live, best, alpha), back

    alpha, backs = lax.scan(fwd, alpha0, jnp.arange(1, T))
    if include_bos_eos_tag:
        stop = trans[:, -2][None, :]
        # add the STOP transition at each sequence's final step only
        alpha = alpha + stop

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)   # [B]

    def bwd(carry, xs):
        tag = carry                                     # [B]
        back, t = xs                                    # back: [B, N]
        prev = jnp.take_along_axis(back, tag[:, None], 1)[:, 0]
        live = t < lengths                              # step t exists
        tag_out = jnp.where(live, prev.astype(jnp.int32), tag)
        return tag_out, tag

    ts = jnp.arange(1, T)
    # path_rev holds tags for steps T-1..1; the final carry is step 0
    first, path_rev = lax.scan(bwd, last_tag, (backs[::-1], ts[::-1]))
    path = jnp.concatenate([first[:, None], path_rev[::-1].T], axis=1)
    # mask out positions beyond each length with the last valid tag
    # (reference returns only valid positions; fixed [B, T] here with
    # padding repeated — documented deviation for static shapes)
    return scores, path


class ViterbiDecoder:
    """Layer-style wrapper (reference: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
