"""Weight-decay regularizers (reference: python/paddle/regularizer.py).

L2Decay feeds the optimizers' fused decoupled/coupled weight-decay path
(optimizer/__init__.py reads ``_coeff``); L1Decay is applied as a
subgradient term by the same path when ``mode == "l1"``.
"""
from __future__ import annotations

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    mode = "l2"
    _coeff = 0.0

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    mode = "l1"


class L2Decay(WeightDecayRegularizer):
    mode = "l2"
