"""PyLayer: user-defined forward/backward (paddle.autograd.PyLayer).

(reference: python/paddle/autograd/py_layer.py — used heavily by the
fleet parallel layers, e.g. mp_ops._c_identity and the sequence-parallel
Scatter/Gather PyLayers.)
"""
from __future__ import annotations

from typing import Any

from . import engine
from ..tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError(f"call {cls.__name__}.apply(...), not the class")


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        in_tensors = [a for a in args if isinstance(a, Tensor)] + [
            v for v in kwargs.values() if isinstance(v, Tensor)]
        requires_grad = engine.is_grad_enabled() and any(
            not t.stop_gradient for t in in_tensors)
        with engine.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_tensors = [o if isinstance(o, Tensor) else Tensor(o) for o in out_list]
        if requires_grad:
            for t in out_tensors:
                t.stop_gradient = False

            def backward_fn(*gout_values):
                gouts = tuple(Tensor(g, stop_gradient=True) for g in gout_values)
                with engine.no_grad():
                    gins = cls.backward(ctx, *gouts)
                if not isinstance(gins, (tuple, list)):
                    gins = (gins,)
                out = []
                for g in gins:
                    out.append(g._value if isinstance(g, Tensor) else g)
                return tuple(out)

            engine.record_custom(
                cls.__name__, backward_fn, in_tensors, out_tensors,
                tuple(t._value for t in out_tensors)
                if multi else out_tensors[0]._value)
        return tuple(out_tensors) if multi else out_tensors[0]
