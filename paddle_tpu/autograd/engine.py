"""Tape-based reverse-mode autograd engine.

TPU-native analog of the reference's eager autograd
(reference: paddle/fluid/eager/grad_node_info.h:53,197 GradNodeBase/Edge;
paddle/fluid/eager/backward.cc egr::Backward — queue-based engine with
dependency counting; paddle/fluid/eager/autograd_meta.h:61).

Design differences from the reference, driven by XLA:
- Grad kernels are pure JAX functions; each node's backward is either an
  explicit registered grad kernel or a generic jax.vjp of the forward
  (jit-cached per op — see core/registry.py). Saved "TensorWrapper"s are
  simply the forward input/output jax.Arrays (no-copy, immutable).
- The same tape runs under an enclosing jax.jit trace: recording and
  replay happen at Python level on Tracers, so `loss.backward()` inside a
  traced train step emits the backward ops into the *same* XLA program —
  this is how whole-step compilation (jit.to_static) gets a single fused
  graph with no eager overhead.
"""
from __future__ import annotations

import contextlib
import threading
from collections import deque
from typing import Any, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..core.registry import OpCall, run_grad

__all__ = [
    "GradNode",
    "backward",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "record_op",
    "register_backward_end_callback",
    "unregister_backward_end_callback",
]

# callbacks fired after every backward() completes (e.g. the bucketed
# DataParallel Reducer flushes leftover partial buckets here — the
# analog of the reference Reducer's finalize_backward)
_backward_end_callbacks: List = []


def register_backward_end_callback(cb) -> None:
    _backward_end_callbacks.append(cb)


def unregister_backward_end_callback(cb) -> None:
    try:
        _backward_end_callbacks.remove(cb)
    except ValueError:
        pass

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool) -> None:
    _state.grad_enabled = bool(mode)


class _GradModeGuard(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def no_grad():
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""
    return _GradModeGuard(False)


def enable_grad():
    return _GradModeGuard(True)


class GradNode:
    """One recorded op on the tape (analog of GradNode<Op> in eager_gen).

    ``edges[i]`` routes the grad of tensor-input i to its producer:
      None                      — input does not require grad
      ("leaf", tensor)          — accumulate into tensor.grad
      ("node", node, out_idx)   — flows to producer node's output slot
    """

    __slots__ = ("name", "call", "in_values", "out_values", "edges", "n_outputs",
                 "_hooks")

    def __init__(self, call: OpCall, in_values, out_values, edges):
        self.name = call.opdef.name
        self.call = call
        self.in_values = in_values
        self.out_values = out_values if isinstance(out_values, tuple) else (out_values,)
        self.edges = edges
        self.n_outputs = len(self.out_values)
        self._hooks = None

    def apply(self, out_grads: List[Optional[Any]]) -> Tuple[Optional[Any], ...]:
        if self.call is None:
            raise RuntimeError(
                f"backward through {self.name} a second time: the graph was "
                "released after .backward(); pass retain_graph=True to keep it")
        full = tuple(
            g if g is not None else jnp.zeros_like(v)
            for g, v in zip(out_grads, self.out_values)
        )
        # Match the forward's output structure for jax.vjp (ops return a
        # single array or a tuple of >=2 — see core/registry.py convention).
        structured = full if self.n_outputs > 1 else full[0]
        return run_grad(self.call, self.in_values, _raw_out(self), structured)

    def release(self):
        self.call = None
        self.in_values = None
        self.out_values = None
        self.edges = ()

    def __repr__(self):
        return f"GradNode({self.name})"


def _raw_out(node: GradNode):
    return node.out_values if node.n_outputs > 1 else node.out_values[0]


class _CustomNode(GradNode):
    """Node whose backward is a user fn (PyLayer, collectives, recompute)."""

    __slots__ = ("backward_fn",)

    def __init__(self, name, backward_fn, out_values, edges):
        self.name = name
        self.call = None
        self.in_values = None
        self.out_values = out_values if isinstance(out_values, tuple) else (out_values,)
        self.edges = edges
        self.n_outputs = len(self.out_values)
        self.backward_fn = backward_fn
        self._hooks = None

    def apply(self, out_grads):
        if self.backward_fn is None:
            raise RuntimeError(
                f"backward through {self.name} a second time: the graph was "
                "released after .backward(); pass retain_graph=True to keep it")
        full = tuple(
            g if g is not None else jnp.zeros_like(v)
            for g, v in zip(out_grads, self.out_values)
        )
        grads = self.backward_fn(*full)
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        return tuple(grads)

    def release(self):
        self.backward_fn = None
        self.out_values = None
        self.edges = ()


def record_op(call: OpCall, in_tensors, out_tensors, out_values) -> None:
    """Attach a GradNode to the outputs of an executed op (tape record)."""
    edges = []
    for t in in_tensors:
        if t is None or t.stop_gradient:
            edges.append(None)
        elif t._grad_node is not None:
            edges.append(("node", t._grad_node, t._out_idx))
        else:
            edges.append(("leaf", t))
    node = GradNode(call, call.in_values, out_values, edges)
    for i, t in enumerate(out_tensors):
        t._grad_node = node
        t._out_idx = i


def record_custom(name, backward_fn, in_tensors, out_tensors, out_values) -> None:
    """Record a custom-backward node (PyLayer / collective ops)."""
    edges = []
    for t in in_tensors:
        if t is None or t.stop_gradient:
            edges.append(None)
        elif t._grad_node is not None:
            edges.append(("node", t._grad_node, t._out_idx))
        else:
            edges.append(("leaf", t))
    node = _CustomNode(name, backward_fn, out_values, edges)
    for i, t in enumerate(out_tensors):
        t._grad_node = node
        t._out_idx = i


def backward(tensors: Sequence, grad_tensors: Optional[Sequence] = None,
             retain_graph: bool = False) -> None:
    """Run reverse accumulation from ``tensors`` (egr::Backward analog).

    Queue-based with per-node dependency counting, matching the engine
    strategy of backward.cc: a node runs only once all grads flowing into
    its output slots (from already-processed consumers) are accumulated.
    """
    from ..tensor import Tensor  # local import to avoid cycle

    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    buffers = {}    # node -> per-output-slot accumulated grads
    pending = {}    # node -> number of unprocessed consumer edges
    roots = []

    def seed(t: Tensor, g):
        if g is None:
            g = jnp.ones_like(t._value)
        elif isinstance(g, Tensor):
            g = g._value
        if t._grad_node is None:
            if not t.stop_gradient:
                _accumulate_leaf(t, g)
            return
        node, idx = t._grad_node, t._out_idx
        buf = buffers.setdefault(node, [None] * node.n_outputs)
        buf[idx] = g if buf[idx] is None else buf[idx] + g
        roots.append(node)

    for t, g in zip(tensors, grad_tensors):
        seed(t, g)

    # Discover reachable graph + consumer counts.
    visited = set()
    stack = list(roots)
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        pending.setdefault(node, 0)
        for e in node.edges:
            if e is not None and e[0] == "node":
                producer = e[1]
                pending[producer] = pending.get(producer, 0) + 1
                stack.append(producer)

    queue = deque(n for n in pending if pending[n] == 0)
    processed = []
    while queue:
        node = queue.popleft()
        out_grads = buffers.pop(node, [None] * node.n_outputs)
        in_grads = node.apply(out_grads)
        if node._hooks:
            for hook in node._hooks:
                hook()
        processed.append(node)
        for e, g in zip(node.edges, in_grads):
            if e is None or g is None:
                continue
            if e[0] == "leaf":
                _accumulate_leaf(e[1], g)
            else:
                producer, idx = e[1], e[2]
                buf = buffers.setdefault(producer, [None] * producer.n_outputs)
                buf[idx] = g if buf[idx] is None else buf[idx] + g
                pending[producer] -= 1
                if pending[producer] == 0:
                    queue.append(producer)

    for cb in list(_backward_end_callbacks):
        cb()

    if not retain_graph:
        for node in processed:
            node.release()


def _accumulate_leaf(t, g) -> None:
    from ..tensor import Tensor
    from ..framework.selected_rows import SelectedRows

    if isinstance(g, SelectedRows):
        # row-sparse leaf gradient (sparse embedding): stays sparse
        # while possible — concat on sparse+sparse, densify on mixing
        # with a dense grad or with grad hooks (hooks see dense Tensors)
        if t._grad_hooks:
            g = g.to_dense_value()
        elif t.grad is None:
            t.grad = g
            return
        elif isinstance(t.grad, SelectedRows):
            t.grad = SelectedRows(
                jnp.concatenate([t.grad.rows, g.rows]),
                jnp.concatenate([t.grad.values, g.values]), g.height)
            return
        else:
            t.grad = Tensor(t.grad._value + g.to_dense_value(),
                            stop_gradient=True)
            return
    elif isinstance(t.grad, SelectedRows):
        t.grad = Tensor(t.grad.to_dense_value(), stop_gradient=True)
    if t._grad_hooks:
        gt = Tensor(g, stop_gradient=True)
        for hook in t._grad_hooks:
            res = hook(gt)
            if res is not None:
                gt = res
        g = gt._value
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._value + g, stop_gradient=True)


# ---------------------------------------------------------------------------
# Higher-order backward (create_graph)
# ---------------------------------------------------------------------------


class _TapedFnNode(GradNode):
    """A grad-of-grad node: stores a PURE fn + operand values, so it can
    be applied (first order) or re-taped (any higher order) — the
    replayable analog of the reference's generated double_grad nodes."""

    __slots__ = ("fn",)

    def __init__(self, name, fn, in_values, out_values, edges):
        self.name = name
        self.fn = fn
        self.call = None
        self.in_values = tuple(in_values)
        self.out_values = out_values if isinstance(out_values, tuple) \
            else (out_values,)
        self.edges = edges
        self.n_outputs = len(self.out_values)
        self._hooks = None

    def apply(self, out_grads):
        import jax

        if self.fn is None:
            raise RuntimeError(
                f"backward through {self.name} a second time: the graph "
                "was released; pass retain_graph=True to keep it")
        full = tuple(
            g if g is not None else jnp.zeros_like(v)
            for g, v in zip(out_grads, self.out_values))
        _, vjp_fn = jax.vjp(lambda *a: self.fn(*a), *self.in_values)
        grads = vjp_fn(full)
        return tuple(
            None if (g is None or g.dtype == jax.dtypes.float0) else g
            for g in grads)

    def release(self):
        self.fn = None
        self.in_values = None
        self.out_values = None
        self.edges = ()


def _tensor_view(val, edge):
    """A Tensor aliasing a recorded input value, wired back into the
    tape via its edge — gives the second-order graph a path to the
    original producers/leaves."""
    from ..tensor import Tensor

    if edge is None:
        return Tensor(val, stop_gradient=True)
    if edge[0] == "leaf":
        return edge[1]
    t = Tensor(val, stop_gradient=False)
    t._grad_node = edge[1]
    t._out_idx = edge[2]
    return t


def backward_create_graph(tensors: Sequence,
                          grad_tensors: Optional[Sequence] = None,
                          leaf_filter=None) -> None:
    """Reverse accumulation where the computed grads are THEMSELVES
    recorded on the tape, so further ``backward``/``grad`` calls
    differentiate through them to ANY order (reference: the double_grad
    node generation of eager_gen — grad ops recorded like forward ops).

    Per-node construction: the map (saved_inputs, out_grads) ->
    in_grads is a pure jax function (re-running the forward ties the
    saved outputs to the inputs), so each first-order grad is emitted
    as a replayable :class:`_TapedFnNode` whose own grads follow the
    same construction recursively. Supported for the registered-op
    tape; custom-backward nodes (PyLayer, collectives, pipeline) raise.

    ``leaf_filter``: optional set of tensor ids — only those leaves
    accumulate (paddle.grad's only-inputs semantics).
    """
    from ..tensor import Tensor

    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    buffers = {}    # node -> per-output-slot accumulated grad TENSORS
    pending = {}
    roots = []

    def add_grad(buf, idx, gt):
        buf[idx] = gt if buf[idx] is None else buf[idx] + gt

    def leaf_acc(t, gt):
        if leaf_filter is not None and id(t) not in leaf_filter:
            return
        _accumulate_leaf_tensor(t, gt)

    def seed(t, g):
        if g is None:
            g = Tensor(jnp.ones_like(t._value), stop_gradient=True)
        elif not isinstance(g, Tensor):
            g = Tensor(jnp.asarray(g), stop_gradient=True)
        if t._grad_node is None:
            if not t.stop_gradient:
                leaf_acc(t, g)
            return
        node, idx = t._grad_node, t._out_idx
        buf = buffers.setdefault(node, [None] * node.n_outputs)
        add_grad(buf, idx, g)
        roots.append(node)

    for t, g in zip(tensors, grad_tensors):
        seed(t, g)

    visited = set()
    stack = list(roots)
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        pending.setdefault(node, 0)
        for e in node.edges:
            if e is not None and e[0] == "node":
                pending[e[1]] = pending.get(e[1], 0) + 1
                stack.append(e[1])

    queue = deque(n for n in pending if pending[n] == 0)
    while queue:
        node = queue.popleft()
        out_gts = buffers.pop(node, [None] * node.n_outputs)
        in_gts = _apply_taped(node, out_gts)
        if node._hooks:
            for hook in node._hooks:
                hook()
        for e, gt in zip(node.edges, in_gts):
            if e is None or gt is None:
                continue
            if e[0] == "leaf":
                leaf_acc(e[1], gt)
            else:
                producer, idx = e[1], e[2]
                buf = buffers.setdefault(producer,
                                         [None] * producer.n_outputs)
                add_grad(buf, idx, gt)
                pending[producer] -= 1
                if pending[producer] == 0:
                    queue.append(producer)
    # create_graph implies the graph stays alive (no release)


def _node_pure_fn(node: GradNode):
    """The node's backward as a PURE function of (operand values,
    out-grad values) -> tuple of in-grads."""
    import jax

    from ..core.registry import run_grad as _run_grad

    if isinstance(node, _TapedFnNode):
        fn = node.fn

        def pure(ivals, ogs):
            _, vjp_fn = jax.vjp(lambda *a: fn(*a), *ivals)
            grads = vjp_fn(tuple(ogs))
            return tuple(
                jnp.zeros_like(iv) if (
                    g is None or g.dtype == jax.dtypes.float0) else g
                for iv, g in zip(ivals, grads))

        return pure

    call = node.call
    multi = node.n_outputs > 1

    def pure(ivals, ogs):
        outs = call.flat_fn(*ivals)  # re-tie outputs to inputs
        grads = _run_grad(call, ivals, outs,
                          tuple(ogs) if multi else ogs[0])
        return tuple(
            jnp.zeros_like(iv) if g is None else g
            for iv, g in zip(ivals, grads))

    return pure


def _apply_taped(node: GradNode, out_grad_tensors):
    """Compute a node's input grads as RECORDED Tensors whose own
    backward is a replayable _TapedFnNode (recursion-closed: works for
    grad-of-grad nodes too, enabling arbitrary order)."""
    import jax

    from ..tensor import Tensor

    if isinstance(node, _CustomNode):
        raise NotImplementedError(
            f"create_graph through '{node.name}': custom-backward nodes "
            "(PyLayer, collectives, pipeline) save value closures that "
            "cannot be re-differentiated w.r.t. the forward inputs; "
            "express the computation with registered ops for "
            "higher-order gradients")
    if node.call is None and not isinstance(node, _TapedFnNode):
        raise RuntimeError(
            f"backward through {node.name} a second time: the graph was "
            "released; use retain_graph/create_graph on the first pass")

    og_full = tuple(
        (g._value if isinstance(g, Tensor) else g)
        if g is not None else jnp.zeros_like(v)
        for g, v in zip(out_grad_tensors, node.out_values))
    ivals = tuple(node.in_values)
    n_in = len(ivals)
    pure = _node_pure_fn(node)

    def flat_fn(*a):
        return pure(a[:n_in], a[n_in:])

    out_vals = flat_fn(*(ivals + og_full))

    in_views = [_tensor_view(v, e) for v, e in zip(ivals, node.edges)]
    og_tensors = [
        g if isinstance(g, Tensor) else Tensor(v, stop_gradient=True)
        for g, v in zip(out_grad_tensors, og_full)]
    out_tensors = [Tensor(v, stop_gradient=False) for v in out_vals]

    # record the replayable grad-of-grad node (edges like record_custom)
    operand_tensors = in_views + og_tensors
    edges = []
    for t in operand_tensors:
        if t is None or t.stop_gradient:
            edges.append(None)
        elif t._grad_node is not None:
            edges.append(("node", t._grad_node, t._out_idx))
        else:
            edges.append(("leaf", t))
    gnode = _TapedFnNode(f"{node.name}_grad", flat_fn,
                         ivals + og_full, tuple(out_vals), edges)
    for i, t in enumerate(out_tensors):
        t._grad_node = gnode
        t._out_idx = i
    # inputs that don't require grad yield None (parity with apply())
    return [t if e is not None else None
            for t, e in zip(out_tensors, node.edges)]


def _accumulate_leaf_tensor(t, gt) -> None:
    if t._grad_hooks:
        for hook in t._grad_hooks:
            res = hook(gt)
            if res is not None:
                gt = res
    if t.grad is None:
        t.grad = gt
    else:
        t.grad = t.grad + gt
