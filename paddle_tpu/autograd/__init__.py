"""Autograd public API (paddle.autograd analog)."""
from .engine import (backward, enable_grad, is_grad_enabled, no_grad,
                     set_grad_enabled)
from .pylayer import PyLayer, PyLayerContext

__all__ = ["backward", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "grad"]


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """paddle.grad — compute grads of outputs wrt inputs. With
    ``create_graph=True`` the returned grads are themselves recorded on
    the tape, so a second backward differentiates through them
    (higher-order AD; see engine.backward_create_graph). Leaf .grad of
    other tensors is snapshot/restored, matching observable semantics
    for the common cases."""
    from .engine import backward_create_graph

    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    saved = [t.grad for t in inputs]
    for t in inputs:
        t.grad = None
    gts = grad_outputs if isinstance(grad_outputs, (list, tuple)) else (
        [grad_outputs] * len(outputs) if grad_outputs is not None else None)
    if create_graph:
        # only-inputs semantics: other leaves' .grad stays untouched
        backward_create_graph(list(outputs), gts,
                              leaf_filter={id(t) for t in inputs})
    else:
        backward(list(outputs), gts, retain_graph=retain_graph)
    grads = [t.grad for t in inputs]
    for t, s in zip(inputs, saved):
        t.grad = s
    if not allow_unused:
        for g, t in zip(grads, inputs):
            if g is None:
                raise RuntimeError("a gradient is unused; pass allow_unused=True")
    return grads
