"""Discrete Fourier transforms (paddle.fft analog).

(reference: python/paddle/fft.py over phi fft kernels
paddle/phi/kernels/fft_kernel.h — cuFFT/onemkl dynload; here every
transform lowers to XLA's native FFT HLO, differentiable end to end.)
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import def_op

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "hfftn", "ihfftn",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq",
]


def _mk1(name, fn):
    @def_op(name)
    def op(x, n=None, axis=-1, norm="backward"):
        return fn(x, n=n, axis=int(axis), norm=str(norm))
    op.__name__ = name
    op.__qualname__ = name
    return op


def _mk2(name, fn):
    @def_op(name)
    def op(x, s=None, axes=(-2, -1), norm="backward"):
        return fn(x, s=s, axes=tuple(axes), norm=str(norm))
    op.__name__ = name
    op.__qualname__ = name
    return op


def _mkn(name, fn):
    @def_op(name)
    def op(x, s=None, axes=None, norm="backward"):
        return fn(x, s=s, axes=axes, norm=str(norm))
    op.__name__ = name
    op.__qualname__ = name
    return op


fft = _mk1("fft", jnp.fft.fft)
ifft = _mk1("ifft", jnp.fft.ifft)
rfft = _mk1("rfft", jnp.fft.rfft)
irfft = _mk1("irfft", jnp.fft.irfft)
hfft = _mk1("hfft", jnp.fft.hfft)
ihfft = _mk1("ihfft", jnp.fft.ihfft)
fft2 = _mk2("fft2", jnp.fft.fft2)
ifft2 = _mk2("ifft2", jnp.fft.ifft2)
rfft2 = _mk2("rfft2", jnp.fft.rfft2)
irfft2 = _mk2("irfft2", jnp.fft.irfft2)
fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


@def_op("fftshift")
def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


@def_op("ifftshift")
def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


@def_op("fftfreq", differentiable=False)
def fftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.fftfreq(int(n), d=float(d))
    return out.astype(dtype) if dtype is not None else out


@def_op("rfftfreq", differentiable=False)
def rfftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    return out.astype(dtype) if dtype is not None else out


def _mk_h2(name, base1d):
    @def_op(name)
    def op(x, s=None, axes=(-2, -1), norm="backward"):
        axes = tuple(axes)
        ns = [None] * len(axes) if s is None else list(s)
        if base1d is jnp.fft.hfft:
            # complex input: fft the leading axes, hermitian-fft last
            out = x
            for ax, n in zip(axes[:-1], ns[:-1]):
                out = jnp.fft.fft(out, n=n, axis=int(ax), norm=str(norm))
            return base1d(out, n=ns[-1], axis=int(axes[-1]),
                          norm=str(norm))
        # ihfft needs the REAL input on the last axis first, then the
        # remaining axes get complex inverse ffts
        out = base1d(x, n=ns[-1], axis=int(axes[-1]), norm=str(norm))
        for ax, n in zip(axes[:-1], ns[:-1]):
            out = jnp.fft.ifft(out, n=n, axis=int(ax), norm=str(norm))
        return out
    op.__name__ = name
    op.__qualname__ = name
    return op


hfft2 = _mk_h2("hfft2", jnp.fft.hfft)
ihfft2 = _mk_h2("ihfft2", jnp.fft.ihfft)


def _hn_axes(x, s, axes):
    if axes is not None:
        return tuple(axes)
    # numpy/reference semantics: with s given, the LAST len(s) axes
    if s is not None:
        return tuple(range(-len(tuple(s)), 0))
    return tuple(range(-x.ndim, 0))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return hfft2(x, s=s, axes=_hn_axes(x, s, axes), norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return ihfft2(x, s=s, axes=_hn_axes(x, s, axes), norm=norm)
