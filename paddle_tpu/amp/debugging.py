"""AMP accuracy debugging tools.

(reference: python/paddle/amp/debugging.py — collect_operator_stats,
TensorCheckerConfig/enable_tensor_checker, check_numerics;
FLAGS_check_nan_inf hooks fluid/eager/nan_inf_utils.h:38 after every
eager op. Here the same chokepoint is core/dispatch.py: an op observer
counts dispatches by dtype, and the existing check_nan_inf flag scans
op outputs inside the jit-cached kernels.)
"""
from __future__ import annotations

import contextlib
from collections import defaultdict
from typing import Dict, Optional

import jax
import numpy as np
import jax.numpy as jnp

from ..core import dispatch as _dispatch
from ..core import flags as _flags
from ..tensor import Tensor

__all__ = ["collect_operator_stats", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "check_numerics",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "DebugMode"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


_stats: Optional[Dict[str, Dict[str, int]]] = None


def _observer(op_name, conv_args):
    dt = "other"
    for a in conv_args:
        if hasattr(a, "dtype"):
            dt = str(a.dtype)
            break
    _stats[op_name][dt] += 1


def enable_operator_stats_collection():
    """(reference debugging.py enable_operator_stats_collection)."""
    global _stats
    _stats = defaultdict(lambda: defaultdict(int))
    _dispatch._op_observer = _observer


def disable_operator_stats_collection():
    global _stats
    _dispatch._op_observer = None
    if _stats:
        print(f"{'op':<32} {'dtype':<12} {'calls':>8}")
        for op, by_dt in sorted(_stats.items()):
            for dt, n in sorted(by_dt.items()):
                print(f"{op:<32} {dt:<12} {n:>8}")
    stats, _stats = _stats, None
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    """(reference debugging.py collect_operator_stats context)."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Count (nan, inf, num) in a tensor; abort mode raises
    (reference debugging.py check_numerics →
    phi/kernels/check_numerics_kernel.h)."""
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if not jnp.issubdtype(v.dtype, jnp.floating):
        return 0, 0, int(np.prod(v.shape) or 1)
    n_nan = int(jnp.sum(jnp.isnan(v)))
    n_inf = int(jnp.sum(jnp.isinf(v)))
    n_num = int(np.prod(v.shape) or 1) - n_nan - n_inf
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT and \
            (n_nan or n_inf):
        raise FloatingPointError(
            f"check_numerics: {op_type or '<tensor>'} {var_name} has "
            f"{n_nan} NaN and {n_inf} Inf values")
    return n_nan, n_inf, n_num


class TensorCheckerConfig:
    """(reference debugging.py TensorCheckerConfig)."""

    def __init__(self, enable: bool = True,
                 debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """Turn on per-op NaN/Inf scanning (FLAGS_check_nan_inf — the
    dispatch layer scans every op output)."""
    if checker_config.enable:
        _flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    _flags.set_flags({"FLAGS_check_nan_inf": False})
