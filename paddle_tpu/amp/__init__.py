"""Automatic mixed precision.

(reference: python/paddle/amp/auto_cast.py:856 auto_cast,
amp/grad_scaler.py:41,619 GradScaler; AMP insertion point in generated
eager code eager_gen.py:515. Here the insertion point is the dispatch
chokepoint core/dispatch.py::_amp_hook.)

TPU notes: bf16 is the native fast dtype (MXU) and needs NO loss scaling;
GradScaler keeps the fp16 semantics for API parity but becomes a no-op
pass-through when enable=False or dtype=bfloat16 with use_dynamic=False.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Set

import jax
import numpy as np
import jax.numpy as jnp

from ..core import dispatch as _dispatch
from ..core.dtype import convert_dtype
from ..tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler",
           "white_list", "black_list"]

# ops that benefit from low precision (MXU-bound)
WHITE_LIST: Set[str] = {
    "matmul", "linear", "conv2d", "conv1d", "conv2d_transpose", "bmm",
    "fused_gemm_epilogue", "einsum_op", "flash_attention",
    "scaled_dot_product_attention", "addmm",
}
# ops that must stay fp32 (numerically sensitive)
BLACK_LIST: Set[str] = {
    "softmax_with_cross_entropy", "cross_entropy_loss", "log_softmax",
    "exp", "log", "logsumexp", "pow", "square", "sum", "mean",
    "layer_norm", "rms_norm", "batch_norm", "group_norm", "instance_norm",
    "norm", "cumsum",
}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


class _AmpState:
    enabled = False
    dtype = jnp.bfloat16
    level = "O1"
    custom_white = set()
    custom_black = set()


_state = _AmpState()


def _amp_hook(op_name, conv_args, conv_kwargs):
    if not _state.enabled:
        return conv_args, conv_kwargs
    white = (WHITE_LIST | _state.custom_white) - _state.custom_black
    if op_name not in white:
        return conv_args, conv_kwargs
    target = _state.dtype

    def cast(v):
        if isinstance(v, (jax.Array, jax.core.Tracer)) and \
                v.dtype == jnp.float32:
            return v.astype(target)
        return v

    return [cast(a) for a in conv_args], {k: cast(v)
                                          for k, v in conv_kwargs.items()}


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None,
              custom_black_list=None, level: str = "O1", dtype="bfloat16",
              use_promote: bool = True):
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = enable
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    _dispatch._amp_hook = _amp_hook if enable else None
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev
        _dispatch._amp_hook = _amp_hook if _state.enabled else None


amp_guard = auto_cast


def decorate(models, optimizers=None, level: str = "O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to the low dtype (keeping master fp32 weights
    in the optimizer when multi_precision)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
        if optimizers is not None:
            opts = [optimizers] if not isinstance(optimizers, (list, tuple)) \
                else list(optimizers)
            for o in opts:
                o._multi_precision = True
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """(reference: python/paddle/amp/grad_scaler.py:619 — dynamic loss
    scaling with found_inf sync; hybrid-parallel variant syncs found_inf
    across groups.)"""

    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0**15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # device-resident state when driven by ParallelEngine (traced
        # protocol): (scale f32, good i32, bad i32, applied-step i32)
        self._dev = None
        self._dev_global = False  # True once _dev is a committed global
        self._found_inf_dev = None
        self._applied_steps = 0

    def _to_eager(self):
        """Hand device-resident scaler state back to the eager protocol:
        sync the host mirrors, then drop the device copy so subsequent
        engine steps reseed from the (possibly eager-updated) host
        values instead of clobbering them with stale device state."""
        self._sync_from_dev()
        self._dev = None
        self._found_inf_dev = None

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        self._to_eager()
        from ..ops import math as M

        return M.scale(var, scale=self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        self._to_eager()
        inv = 1.0 / self._scale
        found = False
        for p in (optimizer._parameter_list or []):
            if p is not None and p.grad is not None:
                g = p.grad._value * inv
                p.grad._value = g
        self._found_inf = self._check_found_inf(optimizer)

    def _check_found_inf(self, optimizer) -> bool:
        # all-finite test (not abs-sum: summing many f16 grads can
        # overflow on its own). Eager-only — inside a compiled step the
        # engine runs the traced protocol below instead.
        finite = True
        for p in (optimizer._parameter_list or []):
            if p is not None and p.grad is not None:
                finite = finite & jnp.all(jnp.isfinite(
                    p.grad._value.astype(jnp.float32)))
        return not bool(finite)

    # -- traced protocol (ParallelEngine.train_step(scaler=...)) ---------
    def _traced_state(self, fallback_step: int = 0):
        """Scaler state as device scalars, carried through the compiled
        step (reference: hybrid_parallel_gradscaler.py keeps these as
        host floats and syncs found_inf with a blocking allreduce; here
        the whole protocol stays on device — no host round-trip).

        ``fallback_step`` seeds the applied-step counter (used for Adam
        bias correction) when no checkpointed value exists — the engine
        passes the optimizer's step count so a resumed run does not
        restart bias correction at t=1."""
        if self._dev is None:
            self._dev = (jnp.float32(self._scale),
                         jnp.int32(self._good_steps),
                         jnp.int32(self._bad_steps),
                         jnp.int32(self._applied_steps or fallback_step))
            self._dev_global = False
        return self._dev

    def _store_traced(self, out):
        self._dev = tuple(out[:4])
        self._dev_global = True  # jit outputs are committed global arrays
        self._found_inf_dev = out[4]

    @property
    def last_found_inf(self):
        """Whether the most recent engine step hit inf/nan (host sync)."""
        if self._found_inf_dev is not None:
            return bool(self._found_inf_dev > 0)
        return self._found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        optimizer.clear_grad()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def _sync_from_dev(self):
        if self._dev is not None:
            self._scale = float(self._dev[0])
            self._good_steps = int(self._dev[1])
            self._bad_steps = int(self._dev[2])
            self._applied_steps = int(self._dev[3])

    def get_loss_scaling(self) -> float:
        self._sync_from_dev()
        return self._scale

    def set_init_loss_scaling(self, v: float):
        self._sync_from_dev()  # keep counters; only the scale resets
        self._scale = float(v)
        self._dev = None

    def state_dict(self):
        self._sync_from_dev()
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps,
                "applied_steps": self._applied_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
        self._applied_steps = state.get("applied_steps", 0)
        self._dev = None


from . import debugging  # noqa: E402,F401


def is_bfloat16_supported(device=None):
    """bf16 is the native TPU matmul dtype (always true here)."""
    return True


def is_float16_supported(device=None):
    """fp16 compute is emulated on TPU; XLA supports the dtype."""
    return True
