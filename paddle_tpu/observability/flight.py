"""Stall flight-records: the last-N metric snapshots + in-flight named
regions + all-thread stacks, dumped to disk when something hangs.

(reference: CommTaskManager's FLAGS_enable_async_trace dump — when an
NCCL collective times out the manager serializes the in-flight task
queue so the post-mortem shows WHAT was queued, not just that the pod
died. TPU-native equivalent: collectives are compiled into the step, so
the record instead captures the registry's recent snapshots (what the
workload was doing), the semantic region stacks (where in the framework
each thread is), raw python stacks (ground truth), and a memory context
(the paddle_tpu_mem_* / device_memory gauges + fresh per-device
memory_stats — OOM-adjacent stalls answer "how full was HBM").)

The ring is fed automatically: every ``MetricsRegistry.snapshot()``
pushes into it, and the instrumented engines snapshot once per
step/round. ``dump()`` is called by the watchdog's timeout handler
before it raises or tears down — and can be called manually from a
debugger or signal handler.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional

__all__ = ["FlightRecorder", "get_recorder", "dump"]

DEFAULT_DIR_ENV = "PADDLE_TPU_FLIGHT_DIR"
_DEFAULT_DIR = "./flight_records"


def _memory_context() -> Dict[str, Any]:
    """Current memory picture for the flight record: the live values of
    every ``paddle_tpu_mem_*`` / ``paddle_tpu_device_memory_bytes``
    gauge plus fresh per-device ``memory_stats()`` — so an OOM-adjacent
    stall dump answers "how full was HBM" without replaying the
    snapshot ring. Best-effort and lock-timeout-guarded: the dumping
    thread may be the one that wedged while HOLDING the registry lock,
    and a post-mortem must never deadlock on it."""
    out: Dict[str, Any] = {"gauges": {}, "device_memory_stats": {}}
    try:
        from .metrics import get_registry

        reg = get_registry()
        gauges: Dict[str, Any] = {}
        locked = reg._lock.acquire(timeout=0.5)
        try:
            for name, m in list(reg._metrics.items()):
                if not (name.startswith("paddle_tpu_mem_")
                        or name == "paddle_tpu_device_memory_bytes"):
                    continue
                for key, s in list(m._series.items()):
                    lbl = ",".join(f"{k}={v}" for k, v
                                   in zip(m.labelnames, key))
                    gauges[name + (f"{{{lbl}}}" if lbl else "")] = \
                        s[0] if isinstance(s, list) else None
        finally:
            if locked:
                reg._lock.release()
        out["gauges"] = gauges
    except Exception:
        pass
    try:
        import jax

        out["device_memory_stats"] = {
            str(d.id): (d.memory_stats() or {})
            for d in jax.local_devices()}
    except Exception:
        pass
    return out


class FlightRecorder:
    """Bounded ring of registry snapshots + a post-mortem dumper."""

    def __init__(self, maxlen: int = 32):
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.last_dump_path: Optional[str] = None

    def push(self, snapshot: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(snapshot)

    def snapshots(self):
        with self._lock:
            return list(self._ring)

    def thread_stacks(self) -> Dict[str, Any]:
        """Python stacks of every live thread (the os-level ground truth
        under the semantic region stacks)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        for tid, frame in sys._current_frames().items():
            out[f"{names.get(tid, 'unknown')} ({tid})"] = \
                traceback.format_stack(frame)
        return out

    def record(self, reason: str = "") -> Dict[str, Any]:
        """Assemble the flight record (without writing it)."""
        from . import trace

        return {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "inflight_regions": trace.current_regions(),
            "thread_stacks": self.thread_stacks(),
            # memory context (observability/memledger gauges + device
            # stats): OOM-adjacent stalls carry how full HBM was
            "memory": _memory_context(),
            "snapshots": self.snapshots(),
        }

    def dump(self, path: Optional[str] = None, reason: str = "") -> str:
        """Write the flight record; returns the path. Directory from
        ``PADDLE_TPU_FLIGHT_DIR`` (default ./flight_records)."""
        if path is None:
            d = os.environ.get(DEFAULT_DIR_ENV, _DEFAULT_DIR)
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{os.getpid()}_{int(time.time() * 1e3)}.json")
        rec = self.record(reason)
        with open(path, "w") as f:
            json.dump(rec, f, indent=1, default=str)
        # dump() runs on watchdog/monitor threads while owners read
        # the path from the main thread
        with self._lock:
            self.last_dump_path = path
        return path


_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _recorder


def attach(registry) -> None:
    """Wire a registry's snapshot() into the ring (metrics.get_registry
    does this for the global registry)."""
    registry._flight = _recorder


def dump(path: Optional[str] = None, reason: str = "") -> str:
    """Module-level shortcut the watchdog timeout handler calls."""
    return _recorder.dump(path, reason)
