"""Run-level goodput ledger: wall-clock attribution across restarts.

The instrument panel (flops / commledger / memledger / roofline) sees
everything *inside* a step; this module accounts where every second of
a whole — possibly crash-interrupted — run goes. Production training
reports treat goodput (productive step time / total wall time) as a
first-class requirement at scale: a run that computes at 55% MFU but
spends 30% of its life recompiling, stalled on checkpoints, or
restarting after preemptions is a slow run, and none of the per-step
instruments can see it.

Every second is attributed to a CLOSED set of segments::

    compile           tracing + XLA compilation of a new step signature
    step_compute      the productive compiled-step dispatch window
    ckpt_stall        checkpoint work the step loop WAITS on (device->
                      host snapshot; the whole commit in sync mode)
    ckpt_async        background checkpoint writes (overlapped: runs on
                      the writer thread, excluded from the wall sum)
    restore           loading a committed checkpoint back into engines
    recovery_restart  crash-to-resume downtime: the dangling tail of a
                      killed run, closed by the NEXT process
    input_wait        host-side batch production the caller wraps
    idle              unattributed wall time (synthesized at read time)

Segments are recorded through the same region mechanism as
``trace.annotate`` (the flight record shows the current segment) and
append to a crash-durable JSONL journal under the checkpoint base dir:
one ``b`` (begin) line flushed BEFORE a segment runs and one ``e``
(end) line when it closes, so a SIGKILL mid-segment leaves a parseable
journal whose dangling tail the relaunched process closes as
``recovery_restart`` (``attach_dir`` on the same base dir — wired into
``resume_latest`` and ``CheckpointManager``). ``goodput_pct`` therefore
spans restart boundaries: productive step seconds over the wall clock
of the whole run, crashes included.

Foreground segments never overlap: an inner segment (e.g. ``compile``
inside a step) PAUSES the outer one — the journal's closed foreground
intervals are disjoint, so their sum plus ``idle`` equals wall time
exactly. Background segments (``ckpt_async``) carry ``"bg": 1`` and are
reported separately as overlapped seconds.

All host-side wall-clock bookkeeping (``time.time`` — comparable
across processes, unlike perf_counter); nothing here touches traced
code, and an unattached process pays one ``None`` check per segment.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["SEGMENTS", "GoodputLedger", "attach_dir", "attach",
           "current", "detach", "segment", "note_event", "read_journal",
           "summarize", "JOURNAL_NAME"]

# the closed segment taxonomy (idle is synthesized at read time from
# wall - sum(closed foreground segments), never written to the journal)
SEGMENTS = ("compile", "step_compute", "ckpt_stall", "ckpt_async",
            "restore", "recovery_restart", "input_wait", "idle")

JOURNAL_NAME = "goodput.jsonl"


class GoodputLedger:
    """One run's wall-clock journal (append-only JSONL, crash-durable).

    Opening a path whose journal already holds events from a PREVIOUS
    process is a resume: the dangling tail (a crashed segment's ``b``
    without its ``e``, or the gap after the last event) is closed as
    ``recovery_restart`` spanning crash-to-resume. Within one process,
    re-attaching the same path reuses the live ledger (``attach_dir``)
    so a second CheckpointManager never fakes a restart.
    """

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        # foreground segment stack: [(name, t0, meta)] — an inner
        # begin closes the outer's elapsed part; the outer resumes
        # when the inner ends (disjoint closed intervals by design)
        self._stack: List[Any] = []
        self._totals: Dict[str, float] = {}
        self._bg_totals: Dict[str, float] = {}
        self._events = 0
        self._start_ts: Optional[float] = None
        self._restarts = 0
        prior = read_journal(self.path) if os.path.exists(self.path) \
            else []
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._f = open(self.path, "a")
        now = time.time()
        if prior:
            self._replay(prior)
            # crash-to-resume downtime: from the last thing the dead
            # process journaled (a dangling begin, or its last event)
            # to this process's first breath
            tail = _tail_ts(prior)
            if tail is not None and now > tail:
                self._append({"ev": "e", "seg": "recovery_restart",
                              "t0": tail, "t1": now})
                self._totals["recovery_restart"] = \
                    self._totals.get("recovery_restart", 0.0) \
                    + (now - tail)
            self._restarts += 1
        if self._start_ts is None:
            self._start_ts = now
        self._append({"ev": "run", "ts": now, "pid": os.getpid(),
                      "resumed": bool(prior)})

    def _replay(self, records: List[Dict[str, Any]]) -> None:
        for r in records:
            if r.get("ev") == "run" and self._start_ts is None:
                self._start_ts = float(r["ts"])
            elif r.get("ev") == "e":
                tot = self._bg_totals if r.get("bg") else self._totals
                tot[r["seg"]] = tot.get(r["seg"], 0.0) \
                    + max(float(r["t1"]) - float(r["t0"]), 0.0)
            if r.get("ev") == "run" and r.get("resumed"):
                self._restarts += 1
            if r.get("ev") == "h":
                self._events += 1

    # -- journal I/O -----------------------------------------------------
    def _append(self, rec: Dict[str, Any]) -> None:
        """One JSON line + flush: flushed bytes reach the kernel, so a
        SIGKILL (the preemption model) never loses them; only a machine
        crash could, and the resume path tolerates any truncated tail."""
        try:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        except (OSError, ValueError):
            pass        # a dead journal must never take the run down

    def _close_interval(self, seg: str, t0: float, t1: float,
                        bg: bool = False, **extra) -> None:
        if t1 <= t0:
            return
        rec = {"ev": "e", "seg": seg, "t0": t0, "t1": t1}
        if bg:
            rec["bg"] = 1
        rec.update(extra)
        self._append(rec)
        tot = self._bg_totals if bg else self._totals
        tot[seg] = tot.get(seg, 0.0) + (t1 - t0)

    # -- the segment protocol --------------------------------------------
    def begin(self, seg: str, **meta) -> None:
        now = time.time()
        with self._lock:
            if self._stack:
                name, t0, m = self._stack[-1]
                self._close_interval(name, t0, now, **m)
            rec = {"ev": "b", "seg": seg, "ts": now}
            rec.update(meta)
            self._append(rec)
            self._stack.append((seg, now, meta))

    def end(self) -> None:
        now = time.time()
        with self._lock:
            if not self._stack:
                return
            name, t0, meta = self._stack.pop()
            self._close_interval(name, t0, now, **meta)
            if self._stack:
                # resume the paused outer segment from here
                name, _, m = self._stack[-1]
                self._stack[-1] = (name, now, m)

    def record_overlapped(self, seg: str, t0: float, t1: float) -> None:
        """A background-thread interval (``ckpt_async``): journaled with
        ``bg: 1``, excluded from the foreground wall identity."""
        with self._lock:
            self._close_interval(seg, t0, t1, bg=True)

    def note_event(self, kind: str, **payload) -> None:
        """Durable anomaly/event record (the health monitor's spike
        events ride here so run_report can draw the timeline)."""
        rec = {"ev": "h", "kind": kind, "ts": time.time()}
        rec.update(payload)
        with self._lock:
            self._append(rec)
            self._events += 1

    # -- reporting -------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """Live totals (open segments counted up to now)."""
        now = time.time()
        with self._lock:
            totals = dict(self._totals)
            if self._stack:
                name, t0, _ = self._stack[-1]
                totals[name] = totals.get(name, 0.0) + (now - t0)
            return _summarize(totals, dict(self._bg_totals),
                              self._start_ts or now, now,
                              self._restarts, self._events)

    def publish(self, metrics: Dict[str, Any]) -> None:
        """Refresh the goodput gauges (catalog.goodput_metrics set)."""
        s = self.summary()
        for seg in SEGMENTS:
            metrics["goodput_segments"].set(
                s["segments"].get(seg, 0.0), segment=seg)
        metrics["goodput_pct"].set(s["goodput_pct"])
        metrics["goodput_wall"].set(s["wall_seconds"])
        metrics["goodput_restarts"].set(float(s["restarts"]))

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


def _tail_ts(records: List[Dict[str, Any]]) -> Optional[float]:
    """The last instant the (dead) writer journaled anything."""
    last = None
    for r in records:
        for k in ("ts", "t1"):
            v = r.get(k)
            if isinstance(v, (int, float)):
                last = v if last is None else max(last, v)
    return last


def _summarize(totals: Dict[str, float], bg: Dict[str, float],
               start: float, end: float, restarts: int,
               events: int) -> Dict[str, Any]:
    wall = max(end - start, 0.0)
    fg_sum = sum(totals.values())
    segments = {seg: round(totals.get(seg, 0.0), 6) for seg in SEGMENTS
                if totals.get(seg)}
    segments["idle"] = round(max(wall - fg_sum, 0.0), 6)
    productive = totals.get("step_compute", 0.0)
    return {
        "wall_seconds": round(wall, 6),
        "segments": segments,
        "segment_pct": {seg: round(100.0 * v / wall, 2) if wall else 0.0
                        for seg, v in segments.items()},
        "overlapped_seconds": {seg: round(v, 6)
                               for seg, v in sorted(bg.items())},
        "productive_step_seconds": round(productive, 6),
        "goodput_pct": round(100.0 * productive / wall, 2) if wall
        else 0.0,
        "restarts": int(restarts),
        "events": int(events),
    }


def read_journal(path: str) -> List[Dict[str, Any]]:
    """Parse a journal leniently: a SIGKILL may truncate the final
    line mid-write — skip anything unparsable instead of failing the
    resume."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Offline summary of a read journal (tools/run_report.py): same
    shape as ``GoodputLedger.summary``, computed purely from closed
    intervals."""
    totals: Dict[str, float] = {}
    bg: Dict[str, float] = {}
    start = end = None
    restarts = events = 0
    for r in records:
        ev = r.get("ev")
        if ev == "run":
            ts = float(r.get("ts", 0.0))
            start = ts if start is None else min(start, ts)
            if r.get("resumed"):
                restarts += 1
        elif ev == "e":
            tot = bg if r.get("bg") else totals
            tot[r["seg"]] = tot.get(r["seg"], 0.0) \
                + max(float(r["t1"]) - float(r["t0"]), 0.0)
        elif ev == "h":
            events += 1
        t = _tail_ts([r])
        if t is not None:
            end = t if end is None else max(end, t)
    if start is None:
        start = end = 0.0
    return _summarize(totals, bg, start, end if end is not None
                      else start, restarts, events)


# ---------------------------------------------------------------------------
# process-current ledger (the engines/checkpoint layers instrument
# against whatever is attached; unattached = everything is a no-op)
# ---------------------------------------------------------------------------
_current: Optional[GoodputLedger] = None
_by_path: Dict[str, GoodputLedger] = {}
_attach_lock = threading.Lock()


def attach_dir(base: str) -> GoodputLedger:
    """Get-or-create the ledger journaling at ``<base>/goodput.jsonl``
    and make it the process-current one. Within a process the same base
    always returns the SAME live ledger (no fake restarts); a fresh
    process opening an existing journal closes its dangling tail as
    ``recovery_restart``."""
    path = os.path.abspath(os.path.join(str(base), JOURNAL_NAME))
    global _current
    with _attach_lock:
        led = _by_path.get(path)
        if led is None:
            led = _by_path[path] = GoodputLedger(path)
        _current = led
        return led


def attach(ledger: Optional[GoodputLedger]) -> None:
    """Make ``ledger`` the process-current one (tests; None detaches)."""
    global _current
    with _attach_lock:
        _current = ledger


def current() -> Optional[GoodputLedger]:
    return _current


def detach() -> None:
    attach(None)


@contextlib.contextmanager
def segment(name: str, **meta):
    """The instrumentation hook: a no-op when no ledger is attached
    (one None check), else one journaled foreground segment. The name
    also rides the ``trace.annotate`` host region stack so a stall
    flight record shows which goodput segment every thread was in."""
    led = _current
    if led is None:
        yield
        return
    from . import trace

    led.begin(name, **meta)
    try:
        with trace.annotate(f"goodput:{name}"):
            yield
    finally:
        led.end()


def note_event(kind: str, **payload) -> None:
    """Durable event on the current ledger (no-op when unattached)."""
    led = _current
    if led is not None:
        led.note_event(kind, **payload)
