"""Optional stdlib HTTP ``/metrics`` + ``/healthz`` endpoint for a
real scrape loop.

``serve_metrics(port)`` starts a daemon-threaded ``http.server``
serving the registry's Prometheus text exposition at ``/metrics``
(and ``/``), so a standard Prometheus scrape config works against a
training or serving process without the JSONL sink. Stdlib only — no
new dependencies — and entirely off the hot path: a scrape calls
``registry.prometheus_text()`` exactly like ``metrics_snapshot()``
does. ``/metrics?names=<prefix>[,<prefix>...]`` narrows the
exposition to metric names under the given prefixes (what a fleet
collector scrapes when it only wants one subsystem's series); both
endpoints declare ``charset=utf-8`` explicitly.

``/healthz`` answers 200 with a tiny JSON liveness payload::

    {"status": "ok", "snapshot_age_seconds": 1.7, "pid": 1234}

``snapshot_age_seconds`` is the time since the registry's last
in-process snapshot — the engines snapshot once per step / serving
tick, so an external scraper can tell a HUNG process (age growing
without bound while the port still answers) from an idle-but-healthy
one. Scrapes of ``/metrics`` deliberately do not refresh the age
(metrics.py ``snapshot(touch=False)``); before any engine tick the
age is ``null``.

Components can degrade the health verdict without owning the endpoint:
``add_health_provider(fn)`` registers a callable returning
``{"component": ..., "status": "ok" | "degraded"}`` (or None to be
pruned — dead engines fall away via weakrefs). ``/healthz`` reports
``"status": "degraded"`` plus the per-component list whenever any
provider does — the ServingEngine registers one that flips to
degraded while it is load-shedding, so an external LB can drain the
replica before users see errors.

    >>> srv = serve_metrics(9100)        # port 0 picks a free port
    >>> srv.port
    9100
    >>> # ... prometheus scrapes http://host:9100/metrics,
    >>> # ... the orchestrator probes /healthz ...
    >>> srv.close()
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from .metrics import MetricsRegistry, get_registry

__all__ = ["MetricsServer", "serve_metrics", "add_health_provider",
           "remove_health_provider", "health_status"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_health_lock = threading.Lock()
_health_providers: list = []


def add_health_provider(fn) -> None:
    """Register a component health callable for /healthz: returns
    ``{"component": str, "status": "ok" | "degraded"}``, or None to be
    pruned (a provider closing over a dead weakref)."""
    with _health_lock:
        if fn not in _health_providers:
            _health_providers.append(fn)


def remove_health_provider(fn) -> None:
    with _health_lock:
        if fn in _health_providers:
            _health_providers.remove(fn)


def health_status() -> dict:
    """Aggregate component health: worst status wins; providers that
    return None (component gone) are pruned."""
    with _health_lock:
        providers = list(_health_providers)
    components, dead = [], []
    for fn in providers:
        try:
            c = fn()
        except Exception:
            continue        # a broken provider must not break liveness
        if c is None:
            dead.append(fn)
            continue
        components.append(c)
    for fn in dead:
        remove_health_provider(fn)
    status = "degraded" if any(
        c.get("status") != "ok" for c in components) else "ok"
    return {"status": status, "components": components}


class MetricsServer:
    """Handle on a running exporter: ``port`` is the bound port (useful
    with ``port=0``), ``close()`` shuts the listener down."""

    def __init__(self, httpd: ThreadingHTTPServer,
                 thread: threading.Thread):
        self._httpd = httpd
        self._thread = thread
        self.port = int(httpd.server_address[1])

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    # context-manager sugar so tests/tools can `with serve_metrics(0):`
    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def serve_metrics(port: int = 0, registry: Optional[MetricsRegistry] = None,
                  host: str = "127.0.0.1") -> MetricsServer:
    """Start the ``/metrics`` endpoint on ``host:port`` (0 = ephemeral)
    serving ``registry`` (default: the process-wide one)."""
    reg = registry or get_registry()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                age = reg.snapshot_age_seconds()
                health = health_status()
                doc = {
                    "status": health["status"],
                    "snapshot_age_seconds":
                        round(age, 3) if age is not None else None,
                    "pid": os.getpid(),
                }
                if health["components"]:
                    doc["components"] = health["components"]
                body = json.dumps(doc).encode("utf-8")
                ctype = "application/json; charset=utf-8"
            elif path in ("/", "/metrics"):
                # ?names=<prefix>[,<prefix>...] filters the exposition
                # by metric-name prefix (a fleet collector scraping
                # only paddle_tpu_serving_* pays for just that); the
                # filtered read is still snapshot(touch=False) inside
                # prometheus_text, so scrapes never mask a hung engine
                prefixes = [p for n in parse_qs(query).get("names", [])
                            for p in n.split(",") if p] or None
                body = reg.prometheus_text(
                    prefixes=prefixes).encode("utf-8")
                ctype = CONTENT_TYPE
            else:
                self.send_error(404, "only /metrics and /healthz are "
                                     "served")
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass                # scrapes must not spam the train log

    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="metrics-exporter", daemon=True)
    thread.start()
    return MetricsServer(httpd, thread)
