"""Communication ledger: trace-time accounting of every collective.

Everything the hybrid step moves over ICI goes through the traced-
collective shim in ``distributed/collective.py`` (``t_psum`` /
``t_all_gather`` / ``t_psum_scatter`` / ``t_all_to_all`` /
``t_ppermute`` and friends). Each shim call *notes* itself here at
TRACE time — op kind, mesh axes, local shape, dtype, ring size — so
capturing one compilation of a step yields an exact static ledger of
that program's communication, with zero ops added to the compiled
program (the ledger cannot perturb the compile lattice: recording is
host-side bookkeeping that only runs while jax is tracing).

Byte accounting (the closed forms tests pin, per participant, ring
algorithms — the standard lower bound XLA's ICI collectives meet):

====================  =========================================
op                    wire bytes sent per participant
====================  =========================================
psum (all-reduce)     2 * (p-1)/p * payload     (reduce-scatter
                      + all-gather phases; pmean/pmax/pmin same)
all_gather            (p-1) * payload           (payload = the
                      local shard, forwarded p-1 times)
reduce_scatter        (p-1)/p * payload         (payload = the
                      full local input)
all_to_all            (p-1)/p * payload
ppermute              payload                   (one neighbor
                      shift of the whole buffer)
====================  =========================================

``payload`` is the noting call's local input buffer in bytes. The
ledger stores both ``payload_bytes`` and the derived ``wire_bytes``.

Caveats (documented, asserted nowhere): a collective inside a
``lax.scan`` body is traced ONCE and therefore counted once, not
``length`` times. Unrolled Python rings (collective_matmul) and the
flat grad-sync collectives are exact. Scan bodies whose trip count is
statically known opt into exact accounting by wrapping the
``lax.scan`` call in ``scan_trips(length)``: records noted inside
carry ``trips=length`` and every byte/op total (and the exposed-comm
replay) scales by it. Both in-tree comm-bearing scans do this — the
bucketed grad-sync scan (distributed/grad_buckets.py, trips=nb) and
the pipeline ring (fleet/.../pp_layers.py ``_pipe_fn``,
trips=E+S-1 forward ticks), so the forward pp ppermute bytes are
EXACT, not a lower bound. The remaining blind spot is the pipeline's
BACKWARD ring: AD synthesizes the reverse-tick ppermute as the
transpose of the forward one without ever passing through the noting
shim, so it is not recorded at all — the ``{axis=pp}`` totals are
exact for the forward schedule and understate a full train step by
exactly the reverse ring.

The second half of this module is the **exposed-comm attribution**
support: ``ablate(labels)`` switches the shim into a mode where the
named axes' collectives lower to shape-preserving LOCAL ops instead,
so an engine can compile a comm-ablated replay of the same step and
measure how much wall time each axis's communication adds to the
critical path (exposed) versus hides behind compute (overlapped).
``ablation_token()`` participates in the engines' compile keys so
ablated replays never collide with the real program cache.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

__all__ = [
    "CommRecord", "CommLedger", "capture", "note", "wire_bytes",
    "active", "ablate", "ablating", "ablation_token", "scan_trips",
    "quant_wire", "OPS",
]

# canonical op kinds the ledger aggregates under (the {op} label of
# paddle_tpu_comm_bytes_total / paddle_tpu_comm_ops_total)
OPS = ("psum", "pmax", "pmin", "all_gather", "reduce_scatter",
       "all_to_all", "ppermute")


def wire_bytes(op: str, payload_bytes: float, p: int) -> float:
    """Closed-form bytes-on-wire per participant for ``op`` over a
    group of ``p`` members moving a ``payload_bytes`` local buffer."""
    if p <= 1:
        return 0.0
    if op in ("psum", "pmax", "pmin"):
        return 2.0 * (p - 1) / p * payload_bytes
    if op == "all_gather":
        return float((p - 1) * payload_bytes)
    if op in ("reduce_scatter", "all_to_all"):
        return (p - 1) / p * payload_bytes
    if op == "ppermute":
        return float(payload_bytes)
    raise ValueError(f"unknown collective op kind {op!r}")


def _itemsize(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return int(getattr(dtype, "itemsize", 4))


@dataclass(frozen=True)
class CommRecord:
    """One traced collective: everything needed to re-issue it."""

    op: str                      # canonical kind (OPS)
    axes: Tuple[str, ...]        # mesh axis names of the group
    axis: str                    # display label: "+".join(axes)
    shape: Tuple[int, ...]       # local input shape at the call
    dtype: str
    p: int                       # group size (product of axis sizes)
    payload_bytes: int
    wire_bytes: float
    args: Tuple = ()             # op-specific statics (gather axis,
    #                              scatter dim, (split, concat), perm)
    trips: int = 1               # executions per program run: 1 for a
    #                              flat/unrolled call site; the scan
    #                              length for sites noted under
    #                              scan_trips() (bucketed grad sync)
    wire_dtype: str = ""         # dtype actually on the wire (== dtype;
    #                              int8/bfloat16 for quantized payloads)
    payload_ratio: float = 1.0   # wire bytes / the uncompressed-
    #                              equivalent wire bytes of the logical
    #                              collective this record implements
    #                              (quant_comm stamps < 1 via
    #                              quant_wire(); 1.0 = uncompressed)


class CommLedger:
    """The static communication record of ONE compiled program."""

    def __init__(self):
        self.records: List[CommRecord] = []

    def __len__(self):
        return len(self.records)

    def add(self, rec: CommRecord):
        self.records.append(rec)

    def axis_labels(self) -> List[str]:
        return sorted({r.axis for r in self.records})

    def totals(self) -> Dict[Tuple[str, str], Dict[str, float]]:
        """{(axis, op): {"bytes": wire bytes, "payload_bytes": ...,
        "ops": count}} aggregated per execution of the program."""
        out: Dict[Tuple[str, str], Dict[str, float]] = {}
        for r in self.records:
            t = out.setdefault((r.axis, r.op),
                               {"bytes": 0.0, "payload_bytes": 0,
                                "ops": 0})
            t["bytes"] += r.wire_bytes * r.trips
            t["payload_bytes"] += r.payload_bytes * r.trips
            t["ops"] += r.trips
        return out

    def bytes_for(self, axis: Optional[str] = None,
                  op: Optional[str] = None) -> float:
        return sum(r.wire_bytes * r.trips for r in self.records
                   if (axis is None or r.axis == axis)
                   and (op is None or r.op == op))

    def ops_for(self, axis: Optional[str] = None,
                op: Optional[str] = None) -> int:
        return sum(r.trips for r in self.records
                   if (axis is None or r.axis == axis)
                   and (op is None or r.op == op))

    def publish(self, bytes_counter, ops_counter) -> None:
        """Add one execution's worth of this program's traffic to the
        registry counters (called once per step by the engines)."""
        for (axis, op), t in self.totals().items():
            bytes_counter.inc(t["bytes"], axis=axis, op=op)
            ops_counter.inc(t["ops"], axis=axis, op=op)

    def quant_ratios(self) -> Dict[str, float]:
        """Per-axis compressed / uncompressed-equivalent wire-byte
        ratio, for axes carrying at least one quantized record
        (payload_ratio < 1 stamped by quant_comm via quant_wire()).
        The logical denominator folds every record back to its
        uncompressed bytes, so mixed axes (some collectives quantized,
        some not) report the blended ratio. Empty when nothing on this
        program's wire is compressed — the engines only publish the
        paddle_tpu_comm_quant_ratio gauge then."""
        axes = {r.axis for r in self.records
                if getattr(r, "payload_ratio", 1.0) != 1.0}
        out: Dict[str, float] = {}
        for axis in axes:
            wire = logical = 0.0
            for r in self.records:
                if r.axis != axis:
                    continue
                w = r.wire_bytes * r.trips
                wire += w
                logical += w / max(getattr(r, "payload_ratio", 1.0),
                                   1e-12)
            if logical > 0:
                out[axis] = wire / logical
        return out

    def summary(self) -> Dict[str, Any]:
        return {
            "records": len(self.records),
            "axes": self.axis_labels(),
            "totals": {f"{a}/{o}": t
                       for (a, o), t in sorted(self.totals().items())},
        }


class _State(threading.local):
    def __init__(self):
        self.captures: List[CommLedger] = []
        self.ablated: frozenset = frozenset()
        self.trips: int = 1
        self.qratio: float = 1.0


_state = _State()


def active() -> bool:
    """True when any capture or ablation is in effect on this thread
    (the shim's fast path skips all bookkeeping otherwise)."""
    return bool(_state.captures) or bool(_state.ablated)


class _Capture:
    def __enter__(self) -> CommLedger:
        self.ledger = CommLedger()
        _state.captures.append(self.ledger)
        return self.ledger

    def __exit__(self, *exc):
        _state.captures.remove(self.ledger)
        return False


def capture() -> _Capture:
    """Context manager collecting every collective noted while jax
    traces inside it. A cached (already-compiled) execution notes
    nothing — an empty capture means "program reused, keep the stored
    ledger"."""
    return _Capture()


def note(op: str, axes: Iterable[str], shape, dtype, p: int,
         args: Tuple = ()) -> None:
    """Record one collective into every active capture (trace time)."""
    if not _state.captures:
        return
    axes = tuple(axes)
    payload = int(np.prod(shape)) * _itemsize(dtype) if shape else \
        _itemsize(dtype)
    rec = CommRecord(op=op, axes=axes, axis="+".join(axes),
                     shape=tuple(int(s) for s in shape),
                     dtype=str(dtype), p=int(p),
                     payload_bytes=payload,
                     wire_bytes=wire_bytes(op, payload, int(p)),
                     args=tuple(args), trips=int(_state.trips),
                     wire_dtype=str(dtype),
                     payload_ratio=float(_state.qratio))
    for led in _state.captures:
        led.add(rec)


class _ScanTrips:
    def __init__(self, length: int):
        self.length = max(int(length), 1)

    def __enter__(self):
        self.prev = _state.trips
        _state.trips = self.prev * self.length
        return self

    def __exit__(self, *exc):
        _state.trips = self.prev
        return False


def scan_trips(length: int) -> _ScanTrips:
    """While active, every noted collective carries ``trips=length``
    (multiplicative under nesting): wrap a ``lax.scan`` call whose body
    issues collectives and whose trip count is static, and the ledger's
    byte/op totals and the exposed-comm replay account the scan exactly
    instead of the once-traced lower bound."""
    return _ScanTrips(length)


class _QuantWire:
    def __init__(self, ratio: float):
        self.ratio = float(ratio)

    def __enter__(self):
        self.prev = _state.qratio
        _state.qratio = self.ratio
        return self

    def __exit__(self, *exc):
        _state.qratio = self.prev
        return False


def quant_wire(ratio: float) -> _QuantWire:
    """While active, records noted on this thread carry
    ``payload_ratio=ratio`` — the wire bytes of the compressed
    collective divided by the uncompressed-equivalent wire bytes of
    the logical collective it implements. quant_comm wraps the shim
    calls that move its int8/fp8 payloads and bf16 scale sidecars in
    this, so ``CommLedger.quant_ratios()`` (and the
    paddle_tpu_comm_quant_ratio gauge) can report the realized
    compression per axis without guessing from dtypes."""
    return _QuantWire(ratio)


# -- ablation (the exposed-comm replay mode) ------------------------------


class _Ablate:
    def __init__(self, labels):
        self.labels = frozenset(labels)

    def __enter__(self):
        self.prev = _state.ablated
        _state.ablated = self.prev | self.labels
        return self

    def __exit__(self, *exc):
        _state.ablated = self.prev
        return False


def ablate(labels: Iterable[str]) -> _Ablate:
    """While active, the collective shim lowers any collective whose
    axis label ("+".join(axes)) is in ``labels`` to a shape-preserving
    LOCAL op — the comm-ablated replay the exposed-comm profiler times
    against the real step. Compose with the engines' compile keys via
    ``ablation_token()``; never use for numerical work (the replay's
    outputs are wrong on purpose)."""
    return _Ablate(labels)


def ablating(axis_label: str) -> bool:
    return axis_label in _state.ablated


def ablation_token() -> Optional[frozenset]:
    """Hashable compile-key component: None in normal operation, the
    ablated label set inside an ``ablate()`` region — so an engine's
    program cache never serves an ablated executable to a real step
    (or vice versa)."""
    return _state.ablated or None


# -- exposed-comm attribution ---------------------------------------------


@dataclass
class ExposedCommReport:
    """The split of per-axis comm time into exposed vs overlapped.

    ``exposed_seconds[axis]``  = t_full - t_ablated(axis): wall time the
    axis's collectives add to the step's critical path.
    ``replay_seconds[axis]``   = wall time of a standalone replay of the
    SAME collectives (shapes/dtypes/perms from the ledger) issued
    back-to-back: the axis's total comm time with nothing to hide it.
    ``exposed_fraction[axis]`` = exposed / max(replay, exposed): 1.0
    means fully serialized on the critical path, 0.0 fully hidden.
    ``grad_sync_exposed_seconds`` sums the exposed time of the data-
    parallel axes (dp / sharding) — the T3-overlap headline metric.
    """

    step_seconds: float = 0.0
    exposed_seconds: Dict[str, float] = field(default_factory=dict)
    replay_seconds: Dict[str, float] = field(default_factory=dict)
    exposed_fraction: Dict[str, float] = field(default_factory=dict)
    grad_sync_exposed_seconds: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "step_seconds": self.step_seconds,
            "exposed_seconds": dict(self.exposed_seconds),
            "replay_seconds": dict(self.replay_seconds),
            "exposed_fraction": dict(self.exposed_fraction),
            "grad_sync_exposed_seconds": self.grad_sync_exposed_seconds,
        }

    def publish(self, metrics: Dict[str, Any]) -> None:
        """Set the catalog gauges (train_metrics keys)."""
        for ax, v in self.exposed_seconds.items():
            metrics["comm_exposed_seconds"].set(v, axis=ax)
        for ax, v in self.replay_seconds.items():
            metrics["comm_replay_seconds"].set(v, axis=ax)
        for ax, v in self.exposed_fraction.items():
            metrics["comm_exposed_fraction"].set(v, axis=ax)
        metrics["grad_sync_exposed"].set(self.grad_sync_exposed_seconds)


GRAD_SYNC_AXES = ("dp", "sharding")


def build_report(step_seconds: float,
                 exposed: Dict[str, float],
                 replay: Dict[str, float]) -> ExposedCommReport:
    """Assemble the report from raw timings (clamping + fractions)."""
    rep = ExposedCommReport(step_seconds=step_seconds)
    for ax in sorted(set(exposed) | set(replay)):
        e = max(0.0, float(exposed.get(ax, 0.0)))
        r = max(0.0, float(replay.get(ax, 0.0)))
        rep.exposed_seconds[ax] = e
        rep.replay_seconds[ax] = r
        denom = max(r, e)
        rep.exposed_fraction[ax] = (e / denom) if denom > 0 else 0.0
    rep.grad_sync_exposed_seconds = sum(
        v for ax, v in rep.exposed_seconds.items()
        if set(ax.split("+")) & set(GRAD_SYNC_AXES))
    return rep


def replay_callable(records: List[CommRecord], mesh, shard_map_fn,
                    jit_fn):
    """Build a compiled program that issues exactly ``records``'s
    collectives back-to-back over ``mesh`` (zeros payloads, results
    folded into one replicated scalar so nothing is DCE'd) — the
    "total comm time" half of the exposed/overlapped split.

    ``shard_map_fn``/``jit_fn`` are injected (jax.shard_map wrapper and
    jax.jit) so this module stays import-light; the engine passes its
    own.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    sync_axes = tuple(a for a in mesh.axis_names if mesh.shape[a] > 1)

    def body():
        acc = jnp.float32(0.0)
        for r in records:
            # scan-traced records (trips > 1, the bucketed grad-sync
            # scan) replay trip-count times; chaining acc into each
            # payload stops XLA CSE'ing the identical collectives and
            # keeps them back-to-back, matching the real scan
            for _ in range(max(int(getattr(r, "trips", 1)), 1)):
                x = jnp.zeros(r.shape, r.dtype) + \
                    (acc * 0).astype(r.dtype)
                if r.op in ("psum", "pmax", "pmin"):
                    fn = {"psum": lax.psum, "pmax": lax.pmax,
                          "pmin": lax.pmin}[r.op]
                    out = fn(x, r.axes)
                elif r.op == "all_gather":
                    out = lax.all_gather(x, r.axes, axis=r.args[0],
                                         tiled=True)
                elif r.op == "reduce_scatter":
                    out = lax.psum_scatter(x, r.axes,
                                           scatter_dimension=r.args[0],
                                           tiled=True)
                elif r.op == "all_to_all":
                    out = lax.all_to_all(x, r.axes, split_axis=r.args[0],
                                         concat_axis=r.args[1],
                                         tiled=True)
                elif r.op == "ppermute":
                    out = lax.ppermute(
                        x, r.axes[0] if len(r.axes) == 1 else r.axes,
                        perm=[tuple(pr) for pr in r.args[0]])
                else:  # pragma: no cover - OPS is closed
                    continue
                acc = acc + out.ravel()[0].astype(jnp.float32)
        # replicate the scalar so out_specs=P() is valid on any mesh
        if sync_axes:
            acc = lax.pmax(acc, sync_axes)
        return acc

    return jit_fn(shard_map_fn(body, mesh, (), P()))
