"""Crash-durable sampled metrics journal: the registry, over time.

``MetricsRegistry`` is a point-in-time surface — a scrape or a
snapshot shows NOW, and a crashed process takes its history with it.
This module gives every run a durable time axis: a background sampler
thread snapshots the registry every ``interval_s`` seconds and appends
one compact JSON line per sample to ``<dir>/metrics.jsonl`` with the
same flush-first discipline as ``goodput.jsonl`` (each line is written
and flushed before the sampler sleeps again, so a SIGKILL — the
preemption model — never loses a completed sample; the reader skips a
truncated tail line instead of failing). ``tools/fleet_report.py``
and ``tools/run_report.py --merge`` read these journals per host to
reconstruct fleet history no live process can serve.

Journal format (one JSON object per line)::

    {"ev": "run", "ts": ..., "pid": ..., "interval_s": ..., "resumed": b}
    {"ev": "s", "ts": ..., "seq": n, "m": {name: {"t": type, "s": [
        [<labels-dict>, <value-or-histogram-state>], ...]}}}
    {"ev": "c", "ts": ..., "kept": k, "dropped": d}      # compaction

Scalar series journal their float value; histogram series journal the
full mergeable state (count / sum / min / max / per-bucket counts), so
offline percentile reconstruction matches the live registry exactly.

Retention is bounded: when the journal exceeds ``retention_samples``
in-file samples the sampler thread compacts it — newest samples are
kept verbatim, the oldest are dropped behind a ``c`` marker, and the
rewrite goes through a temp file + ``os.replace`` so a kill during
compaction leaves either the old or the new journal, never a torn one.

Query API: ``read_journal`` (lenient), ``query`` (label-filtered
(ts, value) points over a time range) and ``resample`` (alignment to
a fixed step grid) — enough for skew/trend reports without a TSDB.

The sampler publishes its own cost into the registry it samples
(``paddle_tpu_timeseries_*``: samples, journal bytes, cumulative
sample seconds, compactions — catalog.timeseries_metrics), so the
overhead bound is itself observable. Everything here is host-side
python; the sampler never touches traced code, so attaching it cannot
change compiled programs (bench pins zero post-warmup recompiles and
bit-identical losses with the sampler on).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .metrics import MetricsRegistry, get_registry

__all__ = ["MetricsSampler", "JOURNAL_NAME", "read_journal", "samples",
           "query", "resample", "attach_dir", "attach", "current",
           "detach"]

JOURNAL_NAME = "metrics.jsonl"

# journal-growth bound: compaction triggers when the in-file sample
# count crosses this (the newest half survives verbatim)
DEFAULT_RETENTION_SAMPLES = 4096


def _compact_snapshot(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Strip a registry snapshot to its journal form: per metric the
    type tag + per-series labels and mergeable state (help strings and
    derived percentiles stay out of the journal)."""
    out: Dict[str, Any] = {}
    for name, entry in snap["metrics"].items():
        rows = []
        for row in entry["series"]:
            if entry["type"] == "histogram":
                rows.append([row["labels"], {
                    "count": row["count"], "sum": row["sum"],
                    "min": row["min"], "max": row["max"],
                    "buckets": row["buckets"]}])
            else:
                rows.append([row["labels"], row["value"]])
        if rows:
            out[name] = {"t": entry["type"], "s": rows}
    return out


class MetricsSampler:
    """Background registry sampler journaling to ``<dir>/metrics.jsonl``.

    One sampler per journal path per process (``attach_dir`` is
    get-or-create, mirroring the goodput ledger); a fresh process
    appending to an existing journal writes a ``resumed`` run header so
    readers see restart boundaries. ``close()`` stops the thread and
    closes the handle; an unwritable directory disables the sampler
    instead of taking the run down.
    """

    def __init__(self, path: str, registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 5.0,
                 retention_samples: int = DEFAULT_RETENTION_SAMPLES):
        from .catalog import timeseries_metrics

        self.path = str(path)
        self.registry = registry or get_registry()
        self.interval_s = max(float(interval_s), 0.01)
        self.retention_samples = max(int(retention_samples), 16)
        self._metrics = timeseries_metrics(self.registry)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._samples_in_file = 0
        self._journal_bytes = 0
        self._overhead_s = 0.0
        self._compactions = 0
        resumed = os.path.exists(self.path) and \
            os.path.getsize(self.path) > 0
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if resumed:
            prior = read_journal(self.path)
            self._seq = 1 + max(
                (int(r.get("seq", -1)) for r in prior
                 if r.get("ev") == "s"), default=-1)
            self._samples_in_file = sum(
                1 for r in prior if r.get("ev") == "s")
            try:
                self._journal_bytes = os.path.getsize(self.path)
            except OSError:
                self._journal_bytes = 0
        self._f = open(self.path, "a")
        self._append(json.dumps(
            {"ev": "run", "ts": time.time(), "pid": os.getpid(),
             "interval_s": self.interval_s, "resumed": resumed}) + "\n")

    # -- journal I/O -----------------------------------------------------
    def _append(self, line: str) -> None:
        """One pre-serialized line + flush on the held-open handle:
        flushed bytes reach the kernel, so a SIGKILL never loses them
        (the same contract as the goodput ledger's ``_append``)."""
        with self._lock:
            f = self._f
            if f is None:
                return
            try:
                f.write(line)
                f.flush()
            except (OSError, ValueError):
                return      # a dead journal must never take the run down
            self._journal_bytes += len(line)

    # -- sampling --------------------------------------------------------
    def sample_now(self) -> Dict[str, Any]:
        """Take and journal one sample; returns the journaled record.
        Runs on the sampler thread every ``interval_s`` (callers may
        also invoke it directly for an on-demand point)."""
        t0 = time.perf_counter()
        with self._lock:
            seq = self._seq
            self._seq += 1
        # scrape-path snapshot: sampling must never refresh the
        # liveness age a /healthz probe keys on
        snap = self.registry.snapshot(touch=False)
        rec = {"ev": "s", "ts": snap["ts"], "seq": seq,
               "m": _compact_snapshot(snap)}
        self._append(json.dumps(rec) + "\n")
        overhead = time.perf_counter() - t0
        with self._lock:
            self._samples_in_file += 1
            self._overhead_s += overhead
            need_compact = self._samples_in_file > self.retention_samples
            journal_bytes = self._journal_bytes
            overhead_total = self._overhead_s
        m = self._metrics
        m["ts_samples"].inc()
        m["ts_journal_bytes"].set(journal_bytes)
        m["ts_sample_seconds"].set(overhead_total)
        if need_compact:
            self._compact()
        return rec

    def _compact(self) -> None:
        """Rewrite the journal keeping the newest half of the retained
        sample budget (plus run headers and prior compaction markers),
        atomically: temp file, flush+fsync, ``os.replace``. Runs only
        on the sampler thread; the shared handle swaps under the lock,
        all filesystem work stays outside it."""
        records = read_journal(self.path)
        keep_n = max(self.retention_samples // 2, 1)
        sample_idx = [i for i, r in enumerate(records)
                      if r.get("ev") == "s"]
        dropped = set(sample_idx[:-keep_n]) if \
            len(sample_idx) > keep_n else set()
        if not dropped:
            return
        kept = [r for i, r in enumerate(records) if i not in dropped]
        kept.append({"ev": "c", "ts": time.time(),
                     "kept": len(sample_idx) - len(dropped),
                     "dropped": len(dropped)})
        tmp = self.path + ".compact.tmp"
        try:
            with open(tmp, "w") as f:
                for r in kept:
                    f.write(json.dumps(r) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            return
        with self._lock:
            old, self._f = self._f, None
        try:
            old.close()
        except OSError:
            pass
        new_bytes = 0
        try:
            os.replace(tmp, self.path)
            new_f = open(self.path, "a")
            new_bytes = os.path.getsize(self.path)
        except OSError:
            new_f = None
        with self._lock:
            self._f = new_f
            self._samples_in_file = len(sample_idx) - len(dropped)
            self._journal_bytes = new_bytes
            self._compactions += 1
        self._metrics["ts_compactions"].inc()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "MetricsSampler":
        """Start the background sampler thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="metrics-sampler", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_now()

    def stats(self) -> Dict[str, Any]:
        """Live sampler accounting (the bench ``timeseries`` section):
        samples journaled this process, journal bytes on disk,
        cumulative sampler overhead seconds, compactions run."""
        with self._lock:
            return {"samples": self._seq,
                    "journal_bytes": self._journal_bytes,
                    "overhead_seconds": round(self._overhead_s, 6),
                    "compactions": self._compactions}

    def query(self, name: str, labels: Optional[Dict[str, str]] = None,
              t0: Optional[float] = None, t1: Optional[float] = None,
              field: str = "value") -> List[Tuple[float, float]]:
        """Range-query this sampler's own journal (see module
        ``query``)."""
        return query(read_journal(self.path), name, labels=labels,
                     t0=t0, t1=t1, field=field)

    def close(self) -> None:
        """Stop the thread (bounded join) and close the journal."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def __enter__(self) -> "MetricsSampler":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# offline reading + range queries
# ---------------------------------------------------------------------------
def read_journal(path: str) -> List[Dict[str, Any]]:
    """Parse a metrics journal leniently: a SIGKILL may truncate the
    final line mid-write — skip anything unparsable instead of failing
    (every COMPLETED sample is recovered; only the torn tail is lost)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def samples(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Just the sample records, journal order."""
    return [r for r in records if r.get("ev") == "s"]


def _labels_match(row_labels: Dict[str, str],
                  want: Optional[Dict[str, str]]) -> bool:
    if not want:
        return True
    return all(str(row_labels.get(k)) == str(v)
               for k, v in want.items())


def query(records: Iterable[Dict[str, Any]], name: str,
          labels: Optional[Dict[str, str]] = None,
          t0: Optional[float] = None, t1: Optional[float] = None,
          field: str = "value") -> List[Tuple[float, float]]:
    """(ts, value) points for one metric across the journal's samples.

    ``labels`` is a subset filter (a series matches when every given
    pair matches); multiple matching series per sample are SUMMED —
    the per-host rollup a lane plot wants. For histogram series
    ``field`` picks the journaled component (``count`` / ``sum`` /
    ``min`` / ``max``); scalars ignore it. ``t0``/``t1`` bound the
    inclusive time range.
    """
    pts: List[Tuple[float, float]] = []
    for r in samples(records):
        ts = float(r.get("ts", 0.0))
        if t0 is not None and ts < t0:
            continue
        if t1 is not None and ts > t1:
            continue
        entry = r.get("m", {}).get(name)
        if entry is None:
            continue
        acc, hit = 0.0, False
        for row_labels, v in entry.get("s", ()):
            if not _labels_match(row_labels, labels):
                continue
            hit = True
            if isinstance(v, dict):
                acc += float(v.get(field if field != "value"
                                   else "count", 0.0))
            else:
                acc += float(v)
        if hit:
            pts.append((ts, acc))
    return pts


def resample(points: List[Tuple[float, float]], step: float,
             t0: Optional[float] = None, t1: Optional[float] = None,
             how: str = "last", ffill: bool = False
             ) -> List[Tuple[float, Optional[float]]]:
    """Align points onto a fixed ``step`` grid (bins at
    ``floor(ts / step) * step``) so journals sampled on different
    clocks line up for cross-host comparison.

    ``how`` reduces the points inside one bin: ``last`` (gauges),
    ``mean``, ``max``, ``min``, ``sum``. Empty bins carry ``None``, or
    the previous bin's value with ``ffill=True``.
    """
    if step <= 0:
        raise ValueError(f"resample step must be > 0, got {step}")
    if how not in ("last", "mean", "max", "min", "sum"):
        raise ValueError(f"unknown resample reduction {how!r}")
    pts = [(ts, v) for ts, v in points
           if (t0 is None or ts >= t0) and (t1 is None or ts <= t1)]
    if not pts:
        return []
    bins: Dict[float, List[float]] = {}
    for ts, v in pts:
        bins.setdefault((ts // step) * step, []).append(v)
    lo = min(bins) if t0 is None else (t0 // step) * step
    hi = max(bins) if t1 is None else (t1 // step) * step
    out: List[Tuple[float, Optional[float]]] = []
    prev: Optional[float] = None
    b = lo
    while b <= hi + 1e-9:
        vs = bins.get(b)
        if vs:
            v = {"last": vs[-1], "mean": sum(vs) / len(vs),
                 "max": max(vs), "min": min(vs),
                 "sum": sum(vs)}[how]
            prev = v
        else:
            v = prev if ffill else None
        out.append((round(b, 9), v))
        b += step
    return out


# ---------------------------------------------------------------------------
# process-current sampler (mirrors the goodput ledger's attach model:
# same base dir -> same live sampler, never a second thread)
# ---------------------------------------------------------------------------
_current: Optional[MetricsSampler] = None
_by_path: Dict[str, MetricsSampler] = {}
_attach_lock = threading.Lock()


def attach_dir(base: str, interval_s: float = 5.0,
               registry: Optional[MetricsRegistry] = None,
               retention_samples: int = DEFAULT_RETENTION_SAMPLES
               ) -> MetricsSampler:
    """Get-or-create the sampler journaling at ``<base>/metrics.jsonl``
    (started) and make it the process-current one. Within a process
    the same base always returns the SAME live sampler; a fresh
    process appending to an existing journal records a resumed run
    header, so the reader sees restart boundaries."""
    path = os.path.abspath(os.path.join(str(base), JOURNAL_NAME))
    global _current
    with _attach_lock:
        smp = _by_path.get(path)
        if smp is None:
            smp = _by_path[path] = MetricsSampler(
                path, registry=registry, interval_s=interval_s,
                retention_samples=retention_samples).start()
        _current = smp
        return smp


def attach(sampler: Optional[MetricsSampler]) -> None:
    """Make ``sampler`` the process-current one (tests; None detaches)."""
    global _current
    with _attach_lock:
        _current = sampler


def current() -> Optional[MetricsSampler]:
    return _current


def detach() -> None:
    attach(None)
