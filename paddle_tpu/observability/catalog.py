"""The metric catalog: every instrument the engines emit, in one place.

Names, label sets, units, and bucket lattices are API — dashboards and
the scrape config key on them — so they are defined HERE once, mirrored
into ``schema.json``, and pinned by a tier-1 test
(tests/test_observability.py): adding/renaming a metric without
updating the schema fails CI instead of silently breaking dashboards.

All metrics live in the global registry (one process = one exposition);
concurrent engines share series, which is the Prometheus model.
"""
from __future__ import annotations

from typing import Dict

from .metrics import (DEFAULT_LATENCY_BUCKETS, MetricsRegistry,
                      get_registry)

__all__ = ["train_metrics", "serving_metrics", "SCHEMA_PATH"]

SCHEMA_PATH = __file__.rsplit("/", 1)[0] + "/schema.json"

# Sub-second lattice for decode-side latencies (TPOT sits at ~1-50ms on
# chip): denser low end than the generic latency lattice.
_FAST_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def train_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the training instrument set."""
    r = reg or get_registry()
    return {
        "step_seconds": r.histogram(
            "paddle_tpu_train_step_seconds",
            "wall time of one compiled train step (dispatch to return; "
            "on async backends steady-state throughput is the "
            "tokens_per_sec gauge, measured between step entries)",
            unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "steps": r.counter(
            "paddle_tpu_train_steps_total", "compiled train steps run"),
        "tokens": r.counter(
            "paddle_tpu_train_tokens_total",
            "training tokens consumed (samples when the batch carries "
            "no token ids)"),
        "tokens_per_sec": r.gauge(
            "paddle_tpu_train_tokens_per_sec",
            "tokens/s over the last inter-step interval (this process)",
            unit="tokens/s"),
        "pod_tokens_per_sec": r.gauge(
            "paddle_tpu_train_pod_tokens_per_sec",
            "tokens/s summed across all hosts (set by pod_throughput(), "
            "an explicit cross-host all_gather)", unit="tokens/s"),
        "loss": r.gauge(
            "paddle_tpu_train_loss",
            "last fetched train loss (one-step lag: fetched at the next "
            "step so telemetry never blocks the dispatch)"),
        "grad_norm": r.gauge(
            "paddle_tpu_train_grad_norm",
            "last fetched global gradient norm (pre-clip, all shards)"),
        "mfu": r.gauge(
            "paddle_tpu_train_mfu",
            "model-FLOPs utilization estimate (6N convention; 0 on "
            "CPU where peak FLOPs are unknown)"),
        "pp_bubble": r.gauge(
            "paddle_tpu_train_pp_bubble_fraction",
            "analytic pipeline bubble fraction of the attached "
            "schedule, (S-1)/(vpp*M+S-1) — published per step when a "
            "pipelined model is attached, labeled by the virtual-stage "
            "count (realized bubble: tools/pp_schedule_measure.py)",
            labelnames=("pp_vpp",)),
        "compiles": r.counter(
            "paddle_tpu_compiles_total",
            "XLA compiles at instrumented launch sites",
            labelnames=("site",)),
        "cache_hits": r.counter(
            "paddle_tpu_compile_cache_hits_total",
            "compiled-program cache hits at instrumented launch sites",
            labelnames=("site",)),
        "device_memory": r.gauge(
            "paddle_tpu_device_memory_bytes",
            "per-device memory stats from the jax runtime",
            labelnames=("device", "stat"), unit="bytes"),
    }


def serving_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the serving instrument set."""
    r = reg or get_registry()
    return {
        "ttft": r.histogram(
            "paddle_tpu_serving_ttft_seconds",
            "time to first token: submit() to the prefill sample",
            unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "tpot": r.histogram(
            "paddle_tpu_serving_tpot_seconds",
            "time per output token after the first, per finished "
            "request", unit="s", buckets=_FAST_BUCKETS),
        "prefill_seconds": r.histogram(
            "paddle_tpu_serving_prefill_seconds",
            "one bucketed prefill (admission-time)", unit="s",
            buckets=DEFAULT_LATENCY_BUCKETS),
        "decode_round_seconds": r.histogram(
            "paddle_tpu_serving_decode_round_seconds",
            "one shared chunked decode round for the in-flight batch",
            unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "queue_depth": r.gauge(
            "paddle_tpu_serving_queue_depth",
            "requests waiting for admission"),
        "active_slots": r.gauge(
            "paddle_tpu_serving_active_slots",
            "in-flight batch rows currently serving a request"),
        "free_pages": r.gauge(
            "paddle_tpu_serving_free_pages",
            "physical KV pages on the free list"),
        "page_occupancy": r.gauge(
            "paddle_tpu_serving_page_occupancy",
            "fraction of the physical page pool in use (trash page "
            "excluded)"),
        "requests": r.counter(
            "paddle_tpu_serving_requests_total",
            "request lifecycle events: submitted / admitted / "
            "backfilled (admitted while other rows were mid-decode) / "
            "evicted (finished, pages freed)",
            labelnames=("event",)),
        "tokens": r.counter(
            "paddle_tpu_serving_tokens_total",
            "tokens produced, by phase", labelnames=("phase",)),
        "compiles": r.counter(
            "paddle_tpu_compiles_total",
            "XLA compiles at instrumented launch sites",
            labelnames=("site",)),
        "cache_hits": r.counter(
            "paddle_tpu_compile_cache_hits_total",
            "compiled-program cache hits at instrumented launch sites",
            labelnames=("site",)),
    }
