"""The metric catalog: every instrument the engines emit, in one place.

Names, label sets, units, and bucket lattices are API — dashboards and
the scrape config key on them — so they are defined HERE once, mirrored
into ``schema.json``, and pinned by a tier-1 test
(tests/test_observability.py): adding/renaming a metric without
updating the schema fails CI instead of silently breaking dashboards.

All metrics live in the global registry (one process = one exposition);
concurrent engines share series, which is the Prometheus model.
"""
from __future__ import annotations

from typing import Dict

from .metrics import (DEFAULT_LATENCY_BUCKETS, MetricsRegistry,
                      get_registry)

__all__ = ["train_metrics", "serving_metrics", "comm_metrics",
           "mem_metrics", "ckpt_metrics", "goodput_metrics",
           "health_metrics", "offload_metrics", "timeseries_metrics",
           "fleet_metrics", "SCHEMA_PATH"]

SCHEMA_PATH = __file__.rsplit("/", 1)[0] + "/schema.json"

# Sub-second lattice for decode-side latencies (TPOT sits at ~1-50ms on
# chip): denser low end than the generic latency lattice.
_FAST_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def comm_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the communication-ledger instrument
    set — shared by the train and serving engines (both publish their
    compiled programs' static comm ledgers through it)."""
    r = reg or get_registry()
    return {
        "comm_bytes": r.counter(
            "paddle_tpu_comm_bytes_total",
            "bytes-on-wire per participant, from the static comm "
            "ledger of every executed compiled program (closed-form "
            "ring accounting; see observability/commledger.py)",
            labelnames=("axis", "op"), unit="bytes"),
        "comm_ops": r.counter(
            "paddle_tpu_comm_ops_total",
            "collectives issued per executed compiled program, from "
            "the static comm ledger (per traced call site; scan "
            "bodies count once)", labelnames=("axis", "op")),
        "comm_exposed_seconds": r.gauge(
            "paddle_tpu_comm_exposed_seconds",
            "per-axis comm wall time EXPOSED on the step's critical "
            "path: t(full step) - t(step with this axis's collectives "
            "ablated), from profile_exposed_comm()",
            labelnames=("axis",), unit="s"),
        "comm_replay_seconds": r.gauge(
            "paddle_tpu_comm_replay_seconds",
            "per-axis total comm time: wall time of a standalone "
            "back-to-back replay of the axis's ledger collectives "
            "(nothing to hide behind)", labelnames=("axis",), unit="s"),
        "comm_exposed_fraction": r.gauge(
            "paddle_tpu_comm_exposed_fraction",
            "exposed / max(replay, exposed) per axis: 1.0 = the "
            "axis's comm is fully serialized on the critical path, "
            "0.0 = fully hidden behind compute",
            labelnames=("axis",)),
        "grad_sync_exposed": r.gauge(
            "paddle_tpu_grad_sync_exposed_seconds",
            "exposed comm seconds summed over the data-parallel axes "
            "(dp/sharding) — the T3-overlap headline: how much of "
            "gradient synchronization the step fails to hide",
            unit="s"),
        "comm_quant_ratio": r.gauge(
            "paddle_tpu_comm_quant_ratio",
            "realized wire compression per axis of the last compiled "
            "program: quantized bytes-on-wire (int8/fp8 payload + "
            "bf16 scale sidecars) / the uncompressed-equivalent bytes "
            "— ~0.25-0.27 for int8 over fp32 at practical chunk "
            "sizes; only published for axes carrying quantized "
            "collectives (distributed/quant_comm.py)",
            labelnames=("axis",)),
    }


def mem_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the HBM memory-ledger instrument set —
    shared by the train and serving engines (both store per-executable
    memory ledgers and a model-state accounting;
    observability/memledger.py)."""
    r = reg or get_registry()
    return {
        "mem_temp": r.gauge(
            "paddle_tpu_mem_temp_bytes",
            "scratch bytes one execution of the compiled program peaks "
            "through mid-step (activations, remat windows, collective "
            "staging), per device — XLA buffer assignment via "
            "memory_analysis()", labelnames=("program",), unit="bytes"),
        "mem_argument": r.gauge(
            "paddle_tpu_mem_argument_bytes",
            "input buffer bytes the compiled program reads (params, "
            "optimizer state, batch), per device",
            labelnames=("program",), unit="bytes"),
        "mem_output": r.gauge(
            "paddle_tpu_mem_output_bytes",
            "result buffer bytes the compiled program writes, per "
            "device", labelnames=("program",), unit="bytes"),
        "mem_alias": r.gauge(
            "paddle_tpu_mem_alias_bytes",
            "bytes shared between arguments and outputs by donation "
            "(buffer aliasing; counted in both classes, subtracted "
            "once from the peak)", labelnames=("program",),
            unit="bytes"),
        "mem_code": r.gauge(
            "paddle_tpu_mem_generated_code_bytes",
            "the executable's own code + embedded constants, per "
            "device", labelnames=("program",), unit="bytes"),
        "mem_state": r.gauge(
            "paddle_tpu_mem_state_bytes",
            "measured per-device model-state footprint by component "
            "(params / grads / optimizer_state / master_weights / "
            "activation_ckpt / host_state), addressable-shard bytes — "
            "ZeRO scatter, pp x vpp chunk ownership, and the host-"
            "offload tier's host-resident split included "
            "(memledger.account_engine)", labelnames=("component",),
            unit="bytes"),
        "mem_drift": r.gauge(
            "paddle_tpu_mem_analytic_drift",
            "(analytic - measured) / measured of the auto_tuner memory "
            "model vs the measured state accounting — the gauge that "
            "validates hbm_gb pruning against reality"),
        "mem_live": r.gauge(
            "paddle_tpu_mem_live_bytes",
            "device bytes held by live jax arrays at the last step "
            "boundary (memledger.live_bytes; the watermark source on "
            "backends without memory_stats)", unit="bytes"),
        "mem_live_peak": r.gauge(
            "paddle_tpu_mem_live_peak_bytes",
            "high-water mark of paddle_tpu_mem_live_bytes over the "
            "engine's lifetime, sampled at step boundaries",
            unit="bytes"),
    }


def offload_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the host-memory offload tier's
    instrument set — shared by the train engine (optimizer moments /
    AMP masters / EF residuals / stored param shards,
    distributed/host_offload.py) and the serving engine (cold KV page
    spill, ``component="kv_page"``). Transfer gauges are CUMULATIVE
    closed-form byte/op totals (per-device addressable-shard bytes per
    slot; page_bytes per spilled page) — bench lines pin them against
    the analytic form exactly."""
    r = reg or get_registry()
    return {
        "bytes": r.gauge(
            "paddle_tpu_offload_transfer_bytes",
            "cumulative host<->device transfer bytes of the offload "
            "tier by state component and direction (d2h = page-out / "
            "spill, h2d = prefetch / fault-back), booked at the "
            "closed form: per-device addressable-shard bytes per slot "
            "(memledger.shard_bytes), page_bytes per KV page",
            labelnames=("component", "direction"), unit="bytes"),
        "ops": r.gauge(
            "paddle_tpu_offload_transfer_ops",
            "cumulative offload-tier transfers by component and "
            "direction (one op per slot / per KV page)",
            labelnames=("component", "direction")),
        "host": r.gauge(
            "paddle_tpu_offload_host_bytes",
            "per-device state bytes currently resident on the host "
            "tier by component — what HBM is NOT holding between "
            "steps (mirrors memledger's host_state accounting "
            "component)", labelnames=("component",), unit="bytes"),
        "prefetch_seconds": r.gauge(
            "paddle_tpu_offload_prefetch_seconds",
            "wall seconds the last dispatch spent re-placing host-"
            "tier state on device (also journaled as an OVERLAPPED "
            "goodput segment, like the async checkpoint writer)",
            unit="s"),
        "spilled_pages": r.gauge(
            "paddle_tpu_offload_spilled_pages",
            "cold KV-cache pages currently resident on the host tier "
            "(spilled out of the fixed device page pool by LRU "
            "eviction; they fault back through the normal page "
            "allocation on a prefix hit)"),
    }


def ckpt_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the checkpoint instrument set —
    published by :class:`distributed.checkpoint.CheckpointManager`
    after every commit (and on ``publish()`` so the age gauge keeps
    counting between saves)."""
    r = reg or get_registry()
    return {
        "age": r.gauge(
            "paddle_tpu_ckpt_last_save_age_seconds",
            "seconds since the last COMMITTED checkpoint (refreshed on "
            "every commit and CheckpointManager.publish(); growing "
            "without bound = saves are failing or stopped)", unit="s"),
        "save_seconds": r.gauge(
            "paddle_tpu_ckpt_save_seconds",
            "wall time of the last completed checkpoint save by phase: "
            "snapshot = device->host shard copy (the only stall the "
            "step loop sees in async mode), write = the commit "
            "protocol's file I/O, total = snapshot + write",
            labelnames=("phase",), unit="s"),
        "save_bytes": r.gauge(
            "paddle_tpu_ckpt_save_bytes",
            "bytes this process's shards contributed to the last "
            "completed checkpoint save", unit="bytes"),
        "last_step": r.gauge(
            "paddle_tpu_ckpt_last_committed_step",
            "training step of the newest committed checkpoint"),
        "pending": r.gauge(
            "paddle_tpu_ckpt_async_pending",
            "async checkpoint saves snapshotted but not yet committed "
            "(writer-thread queue depth; stuck >0 = storage stalled)"),
        "saves": r.counter(
            "paddle_tpu_ckpt_saves_total",
            "checkpoint saves by outcome (committed = the COMMIT "
            "marker hit disk)", labelnames=("result",)),
    }


def goodput_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the run-level goodput instrument set —
    published by the attached :class:`observability.goodput.
    GoodputLedger` (wall-clock attribution across restarts; the
    crash-durable journal under the checkpoint base dir is the source
    of truth, these gauges are its live view)."""
    r = reg or get_registry()
    return {
        "goodput_segments": r.gauge(
            "paddle_tpu_goodput_segment_seconds",
            "cumulative run wall time attributed to each goodput "
            "segment (compile / step_compute / ckpt_stall / ckpt_async "
            "/ restore / recovery_restart / input_wait / idle), "
            "restart-spanning (observability/goodput.py journal)",
            labelnames=("segment",), unit="s"),
        "goodput_pct": r.gauge(
            "paddle_tpu_goodput_pct",
            "productive step seconds / run wall seconds x 100, across "
            "restart boundaries — the run-level goodput headline",
            unit="pct"),
        "goodput_wall": r.gauge(
            "paddle_tpu_goodput_wall_seconds",
            "wall seconds since the run's first journal record, "
            "crashes and restarts included", unit="s"),
        "goodput_restarts": r.gauge(
            "paddle_tpu_goodput_restarts",
            "process restarts the run's goodput journal has absorbed "
            "(each closed a recovery_restart segment)"),
    }


def health_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the training health-monitor instrument
    set (observability/healthmon.py: rolling median+MAD anomaly events
    over loss / grad-norm / step time, cross-host straggler skew)."""
    r = reg or get_registry()
    return {
        "events": r.counter(
            "paddle_tpu_health_events_total",
            "health anomaly events by kind: loss_spike / "
            "grad_norm_spike / loss_nonfinite / step_time_stall "
            "(robust rolling median+MAD detection; each event also "
            "lands in the goodput journal and may dump a flight "
            "record)", labelnames=("kind",)),
        "loss_z": r.gauge(
            "paddle_tpu_health_loss_zscore",
            "robust z-score of the last observed loss against its "
            "rolling window (0 while the window is warming up)"),
        "grad_norm_z": r.gauge(
            "paddle_tpu_health_grad_norm_zscore",
            "robust z-score of the last observed global grad-norm "
            "against its rolling window"),
        "step_time_z": r.gauge(
            "paddle_tpu_health_step_time_zscore",
            "robust z-score of the last observed step time against "
            "its rolling window"),
        "degraded": r.gauge(
            "paddle_tpu_health_degraded",
            "1 while the health monitor is within degraded_window_s "
            "of its last anomaly event (mirrors the /healthz "
            "component verdict), else 0"),
        "step_time_skew": r.gauge(
            "paddle_tpu_health_step_time_skew",
            "(slowest host's step time - median) / median across the "
            "pod, from observe_pod_skew's cross-host all_gather — "
            "0 on a single process; a persistently hot value names a "
            "straggler host"),
        "slowest_host": r.gauge(
            "paddle_tpu_health_slowest_host",
            "process index of the slowest host in the last "
            "observe_pod_skew exchange"),
    }


def timeseries_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the metrics-journal sampler's own
    instrument set (observability/timeseries.py): the sampler's cost
    is itself a metric — and therefore itself journaled — so the
    bounded-overhead contract is observable from the journal alone."""
    r = reg or get_registry()
    return {
        "ts_samples": r.counter(
            "paddle_tpu_timeseries_samples_total",
            "registry samples journaled to metrics.jsonl by this "
            "process's background sampler"),
        "ts_journal_bytes": r.gauge(
            "paddle_tpu_timeseries_journal_bytes",
            "current on-disk size of the metrics.jsonl journal "
            "(bounded by retention_samples + compaction)",
            unit="bytes"),
        "ts_sample_seconds": r.gauge(
            "paddle_tpu_timeseries_sample_seconds",
            "cumulative wall seconds the sampler thread spent taking "
            "and journaling samples (the per-sample overhead bound "
            "bench gates = this / samples_total)", unit="s"),
        "ts_compactions": r.counter(
            "paddle_tpu_timeseries_compactions_total",
            "journal compactions run (atomic rewrite keeping the "
            "newest half of the retention budget behind a 'c' "
            "marker)"),
    }


def fleet_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the fleet collector's self-accounting
    set (observability/fleet.py — the collector process's OWN
    registry; member metrics pass through merged, not re-registered)."""
    r = reg or get_registry()
    return {
        "members": r.gauge(
            "paddle_tpu_fleet_members",
            "fleet members by rollup verdict at the last /healthz "
            "evaluation (degraded covers member-reported degradation, "
            "unreachable scrape targets, and stale liveness ages)",
            labelnames=("state",)),
        "scrapes": r.counter(
            "paddle_tpu_fleet_scrapes_total",
            "member scrape attempts by result",
            labelnames=("result",)),
        "series": r.gauge(
            "paddle_tpu_fleet_merged_series",
            "per-host series feeding the merged fleet exposition at "
            "the last merge"),
        "collect_seconds": r.gauge(
            "paddle_tpu_fleet_collect_seconds",
            "wall seconds of the last scrape sweep over all "
            "url-bearing members", unit="s"),
    }


def train_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the training instrument set."""
    r = reg or get_registry()
    out = comm_metrics(r)
    out.update(mem_metrics(r))
    out.update({f"offload_{k}": v for k, v in offload_metrics(r).items()})
    out.update({f"ckpt_{k}": v for k, v in ckpt_metrics(r).items()})
    out.update(goodput_metrics(r))
    out.update({f"health_{k}": v for k, v in health_metrics(r).items()})
    out.update(timeseries_metrics(r))
    out.update({
        "step_seconds": r.histogram(
            "paddle_tpu_train_step_seconds",
            "wall time of one compiled train step (dispatch to return; "
            "on async backends steady-state throughput is the "
            "tokens_per_sec gauge, measured between step entries)",
            unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "steps": r.counter(
            "paddle_tpu_train_steps_total", "compiled train steps run"),
        "tokens": r.counter(
            "paddle_tpu_train_tokens_total",
            "training tokens consumed (samples when the batch carries "
            "no token ids)"),
        "tokens_per_sec": r.gauge(
            "paddle_tpu_train_tokens_per_sec",
            "tokens/s over the last inter-step interval (this process)",
            unit="tokens/s"),
        "pod_tokens_per_sec": r.gauge(
            "paddle_tpu_train_pod_tokens_per_sec",
            "tokens/s summed across all hosts (set by pod_throughput(), "
            "an explicit cross-host all_gather)", unit="tokens/s"),
        "loss": r.gauge(
            "paddle_tpu_train_loss",
            "last fetched train loss (one-step lag: fetched at the next "
            "step so telemetry never blocks the dispatch)"),
        "grad_norm": r.gauge(
            "paddle_tpu_train_grad_norm",
            "last fetched global gradient norm (pre-clip, all shards)"),
        "grad_buckets": r.gauge(
            "paddle_tpu_train_grad_buckets",
            "gradient-sync buckets the compiled step issues per-bucket "
            "DP/sharding collectives over (T3-style overlap, "
            "sharding_configs['comm_overlap']; 0 = the unbucketed "
            "end-of-backward tail sync — distributed/grad_buckets.py)"),
        "quant_residual_norm": r.gauge(
            "paddle_tpu_train_quant_residual_norm",
            "global L2 norm of the quantized-collective error-feedback "
            "residuals after the last step (gradient mass carried in "
            "the compensation state; fetched with the loss's one-step "
            "lag — only published when quant_comm grad_sync runs with "
            "error_feedback on; distributed/quant_comm.py)"),
        "mfu": r.gauge(
            "paddle_tpu_train_mfu",
            "model-FLOPs utilization estimate (6N convention; 0 on "
            "CPU where peak FLOPs are unknown)"),
        "pp_bubble": r.gauge(
            "paddle_tpu_train_pp_bubble_fraction",
            "analytic pipeline bubble fraction of the attached "
            "schedule, (S-1)/(vpp*M+S-1) — published per step when a "
            "pipelined model is attached, labeled by the virtual-stage "
            "count (realized bubble: tools/pp_schedule_measure.py)",
            labelnames=("pp_vpp",)),
        "compiles": r.counter(
            "paddle_tpu_compiles_total",
            "XLA compiles at instrumented launch sites",
            labelnames=("site",)),
        "cache_hits": r.counter(
            "paddle_tpu_compile_cache_hits_total",
            "compiled-program cache hits at instrumented launch sites",
            labelnames=("site",)),
        "device_memory": r.gauge(
            "paddle_tpu_device_memory_bytes",
            "per-device memory stats from the jax runtime",
            labelnames=("device", "stat"), unit="bytes"),
        "moe_expert_load": r.gauge(
            "paddle_tpu_moe_expert_load",
            "fraction of routed-and-kept tokens landing on each "
            "expert last step, summed over the batch-sharding axes "
            "(1/E everywhere = perfectly balanced routing; fetched "
            "with the loss's one-step lag — observability/moestats.py)",
            labelnames=("layer", "expert")),
        "moe_drop_rate": r.gauge(
            "paddle_tpu_moe_token_drop_rate",
            "fraction of routing slots (tokens x top_k) dropped at "
            "capacity last step, per MoE layer",
            labelnames=("layer",)),
        "moe_aux_loss": r.gauge(
            "paddle_tpu_moe_aux_loss",
            "load-balance auxiliary loss of the last step "
            "(unscaled, averaged over ep ranks), per MoE layer",
            labelnames=("layer",)),
    })
    return out


def serving_metrics(reg: MetricsRegistry = None) -> Dict[str, object]:
    """Register (get-or-create) the serving instrument set."""
    r = reg or get_registry()
    out = comm_metrics(r)
    out.update(mem_metrics(r))
    out.update({f"offload_{k}": v for k, v in offload_metrics(r).items()})
    out.update(timeseries_metrics(r))
    out.update({
        "ttft": r.histogram(
            "paddle_tpu_serving_ttft_seconds",
            "time to first token: submit() to the prefill sample",
            unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "tpot": r.histogram(
            "paddle_tpu_serving_tpot_seconds",
            "time per output token after the first, per finished "
            "request", unit="s", buckets=_FAST_BUCKETS),
        "prefill_seconds": r.histogram(
            "paddle_tpu_serving_prefill_seconds",
            "prefill latency per request: one bucketed admission-time "
            "prefill (legacy), or admit to first token across the "
            "scheduled chunks (chunked mode)", unit="s",
            buckets=DEFAULT_LATENCY_BUCKETS),
        "decode_round_seconds": r.histogram(
            "paddle_tpu_serving_decode_round_seconds",
            "one shared chunked decode round for the in-flight batch",
            unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "unified_round_seconds": r.histogram(
            "paddle_tpu_serving_unified_round_seconds",
            "one unified mixed prefill-chunk + decode dispatch "
            "(chunked-prefill mode: the fixed [B, Sc] ragged program)",
            unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "prefill_chunks": r.counter(
            "paddle_tpu_serving_prefill_chunks_total",
            "prompt chunks fed through the unified step (chunked-"
            "prefill mode; per-chunk token counts ride the request "
            "traces' prefill_chunk spans)"),
        "prefill_stall": r.counter(
            "paddle_tpu_serving_prefill_page_stall_total",
            "rounds a mid-prefill row could not reserve its next "
            "chunk's pages and waited (incremental page reservation; "
            "sustained growth means the pool is undersized for the "
            "admitted mix)"),
        "queue_depth": r.gauge(
            "paddle_tpu_serving_queue_depth",
            "requests waiting for admission"),
        "active_slots": r.gauge(
            "paddle_tpu_serving_active_slots",
            "in-flight batch rows currently serving a request"),
        "free_pages": r.gauge(
            "paddle_tpu_serving_free_pages",
            "physical KV pages on the free list"),
        "page_occupancy": r.gauge(
            "paddle_tpu_serving_page_occupancy",
            "fraction of the physical page pool in use (trash page "
            "excluded)"),
        "requests": r.counter(
            "paddle_tpu_serving_requests_total",
            "request lifecycle events: submitted / admitted / "
            "backfilled (admitted while other rows were mid-decode) / "
            "evicted (finished, pages freed)",
            labelnames=("event",)),
        "shed": r.counter(
            "paddle_tpu_serving_shed_total",
            "requests shed by graceful degradation, by reason: "
            "queue_full (bounded admission queue at max_queue on "
            "submit) / deadline (admission deadline expired while "
            "queued). Shed requests never reach prefill, so their "
            "latency is excluded from the TTFT histogram",
            labelnames=("reason",)),
        "tokens": r.counter(
            "paddle_tpu_serving_tokens_total",
            "tokens produced, by phase", labelnames=("phase",)),
        "compiles": r.counter(
            "paddle_tpu_compiles_total",
            "XLA compiles at instrumented launch sites",
            labelnames=("site",)),
        "cache_hits": r.counter(
            "paddle_tpu_compile_cache_hits_total",
            "compiled-program cache hits at instrumented launch sites",
            labelnames=("site",)),
        "prefix_hit_rate": r.gauge(
            "paddle_tpu_serving_prefix_cache_hit_rate",
            "cumulative prefix-cache hit rate: page-aligned prompt "
            "chunks served from cached KV pages over chunks looked "
            "up at admission (inference/serving.py prefix_cache)"),
        "prefix_pages": r.gauge(
            "paddle_tpu_serving_prefix_cache_pages",
            "registered prefix-cache pages by state: active (held by "
            "at least one slot) / idle (refcount 0, parked on the "
            "reclaim LRU)", labelnames=("state",)),
        "prefix_events": r.counter(
            "paddle_tpu_serving_prefix_cache_events_total",
            "prefix-cache lifecycle events: hit (page mapped into an "
            "admitted slot, zero copy) / registered (completed page "
            "published under its prefix hash) / cow (copy-on-write of "
            "a shared page before a divergent write) / reclaimed "
            "(idle page evicted to the free list under pool "
            "pressure)", labelnames=("event",)),
        "spec_accept_rate": r.gauge(
            "paddle_tpu_serving_spec_accept_rate",
            "cumulative speculative-decoding acceptance: draft tokens "
            "matching the target's greedy argmax chain over draft "
            "tokens proposed"),
        "spec_tokens_per_step": r.gauge(
            "paddle_tpu_serving_spec_tokens_per_step",
            "decode tokens committed per decode-row verify step with "
            "speculative decoding (accepted run + the bonus token; "
            "1.0 means no speculation win)"),
        "stage_seconds": r.histogram(
            "paddle_tpu_serving_request_stage_seconds",
            "per-request lifecycle stage latency (spans): queued = "
            "submit→admit, prefill = admit→first token, decode = "
            "first token→finish, e2e = submit→finish "
            "(observability/spans.py; Chrome-trace export via "
            "ServingEngine.export_request_traces)",
            unit="s", labelnames=("stage",),
            buckets=DEFAULT_LATENCY_BUCKETS),
        "trace_parse_errors": r.counter(
            "paddle_tpu_serving_trace_parse_errors_total",
            "trace identities rejected at submit(), by reason: "
            "malformed_traceparent (header failed the W3C grammar or "
            "carried an all-zero id) / invalid_trace_id (bare trace "
            "id not 32 hex). The request is served under a freshly "
            "minted trace id either way — this counter is how router-"
            "injected headers stay debuggable",
            labelnames=("reason",)),
        "prefix_hash_entries": r.gauge(
            "paddle_tpu_serving_prefix_hash_entries",
            "entries in the prefix-cache page hash table (content-"
            "addressed registered pages; the idle-list length rides "
            "paddle_tpu_serving_prefix_cache_pages{state=\"idle\"}) — "
            "the state router prefix-affinity steering reads"),
        "migrations": r.counter(
            "paddle_tpu_serving_migrations_total",
            "KV page migrations between disaggregated replicas, by "
            "result: ok (imported by a decode replica) / refused "
            "(decode replica had no free slot or pages — backpressure) "
            "/ crc_error (a transferred page payload failed its crc32 "
            "and the request was retried on a fresh replica)",
            labelnames=("result",)),
        "migration_bytes": r.counter(
            "paddle_tpu_serving_migration_bytes_total",
            "bytes moved by KV page migration, ledger-exact at the "
            "closed form pages x page_bytes + the block-table row "
            "(inference/disagg.py; also booked on the comm ledger "
            "under axis \"migrate\")"),
        "migration_seconds": r.histogram(
            "paddle_tpu_serving_migration_seconds",
            "one request's KV page migration: export on the prefill "
            "replica through crc-verified import on the decode "
            "replica", unit="s", buckets=DEFAULT_LATENCY_BUCKETS),
        "router_requests": r.counter(
            "paddle_tpu_router_requests_total",
            "front-door placements per replica, by decision: affinity "
            "(prefix-affinity steering matched registered pages) / "
            "least_loaded (fallback placement) / retry (resubmitted "
            "after a migration crc failure)",
            labelnames=("replica", "decision")),
        "phase_slots": r.gauge(
            "paddle_tpu_router_phase_slots",
            "fleet phase occupancy: in-flight batch rows summed over "
            "the replicas of each phase (prefill / decode / unified)",
            labelnames=("phase",)),
    })
    return out
