"""Semantic device-trace annotations + host-side in-flight regions.

``annotate(name)`` does two jobs at once:

- inside a ``jax.jit``/``shard_map`` trace it opens a
  ``jax.named_scope``, so the XLA metadata (and therefore the
  TensorBoard/Perfetto device trace the TPU profiler captures) carries
  framework names — ``llama/layer3/attention``, ``ag_matmul_ring``,
  ``paged_decode_attention`` — instead of bare HLO ops (the reference
  gets this from its C++ RecordEvent annotations feeding CUPTI),
- on the host it pushes the name on a per-thread region stack, so a
  stall flight-record (flight.py) can report what every thread was
  doing when the watchdog fired — including mid-trace hangs, where the
  region stack shows how deep into the model the tracer got.

The host bookkeeping is plain list push/pop under no lock (each thread
touches only its own stack; the flight dump reads other threads'
stacks racily, which is fine for a post-mortem).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, List

__all__ = ["annotate", "current_regions"]

# tid -> region-name stack. Threads insert their own entry on first
# annotate; the dict itself is only ever appended to (no rebalancing),
# so racy reads from the flight dump see a consistent-enough view.
_regions: Dict[int, List[str]] = {}


@contextlib.contextmanager
def annotate(name: str):
    """Named region: jax.named_scope for the device trace + an in-flight
    marker for stall flight-records. Cheap enough for per-layer use."""
    import jax

    tid = threading.get_ident()
    stack = _regions.get(tid)
    if stack is None:
        stack = _regions[tid] = []
    stack.append(name)
    try:
        with jax.named_scope(name):
            yield
    finally:
        stack.pop()


def current_regions() -> Dict[str, List[str]]:
    """{thread-name (tid): open-region stack}, innermost last — what
    each thread is inside right now (flight records embed this)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, stack in list(_regions.items()):
        if stack:
            out[f"{names.get(tid, 'dead')} ({tid})"] = list(stack)
    return out
