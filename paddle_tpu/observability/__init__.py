"""Unified telemetry for the paddle_tpu stack.

One process-wide ``MetricsRegistry`` (metrics.py) that the three hot
subsystems instrument into:

- **training** — ``distributed.engine.ParallelEngine`` emits per-step
  wall time, tokens/s, loss, grad-norm, an MFU estimate (flops.py),
  device memory stats, and the CompileStats counters; cross-host
  aggregation via ``cross_host_sum`` lets rank 0 report pod throughput,
- **serving**  — ``inference.serving.ServingEngine`` emits TTFT / TPOT
  histograms, queue depth, slot/page-pool occupancy, and
  admission/eviction/backfill counters,
- **traces**   — ``trace.annotate`` stamps ``jax.named_scope`` names
  onto transformer layers, the collective-matmul rings, and the paged-
  attention kernels so XLA/Perfetto device traces carry framework
  names, and mirrors them into host region stacks that
  ``flight.dump()`` (the watchdog's stall flight-record) reports,
- **comm**     — ``commledger`` accounts every collective the traced
  step issues (axis / op / dtype / bytes, via the shim in
  ``distributed/collective.py``) and backs the exposed-comm
  attribution pass (``ParallelEngine.profile_exposed_comm``),
- **memory**   — ``memledger`` attributes per-executable HBM bytes
  (XLA ``memory_analysis``: temp / argument / output / alias / code),
  measures the model-state footprint per device (ZeRO- and
  pp x vpp-aware shard accounting, cross-checked against the
  auto_tuner's analytic model), and joins flops + comm + memory into
  per-step roofline verdicts (compute- / hbm- / ici-bound with
  headroom percentages),
- **spans**    — per-request serving lifecycle traces
  (queued → prefill → decode rounds) in a bounded ring with
  Chrome-trace export (``ServingEngine.export_request_traces``),
- **goodput**  — run-level wall-clock attribution (``goodput``): every
  second of a — possibly crash-interrupted — run booked to a closed
  segment set (compile / step_compute / ckpt_stall / ckpt_async /
  restore / recovery_restart / input_wait / idle) in a crash-durable
  JSONL journal under the checkpoint base dir; ``goodput_pct`` spans
  restart boundaries (``tools/run_report.py`` renders the waterfall),
- **health**   — rolling robust (median + MAD) anomaly events over
  loss / grad-norm / step time (``healthmon``): spike events + flight
  records + a degraded ``/healthz`` component + cross-host straggler
  gauges,
- **timeseries** — a crash-durable sampled metrics journal
  (``timeseries``): a background sampler snapshots the registry every
  N seconds into ``metrics.jsonl`` (flush-first, lenient tail reader,
  bounded by compaction) with a label-filtered range-query +
  resampling API (``tools/fleet_report.py`` reads these per host),
- **fleet**    — a stdlib-HTTP cross-host collector (``fleet``):
  scrapes or receives per-host expositions, re-labels series with
  ``host``, serves a merged fleet ``/metrics`` (counters summed,
  gauges min/max/mean, fixed-bucket histograms merged bucket-exactly)
  and a fleet ``/healthz`` rollup (degraded / unreachable / stale
  members).

Exports: Prometheus text exposition + JSONL sink + in-process
snapshots (metrics.py), plus an optional stdlib HTTP ``/metrics``
endpoint (``exporter.serve_metrics``). All instrumentation is
host-side python on fetched scalars or trace-time bookkeeping —
nothing here adds ops to compiled programs, so compile caches stay
exactly as flat as they were without telemetry.
"""
from __future__ import annotations

from .metrics import (Counter, Gauge, Histogram, JsonlSink,  # noqa: F401
                      MetricsRegistry, DEFAULT_LATENCY_BUCKETS,
                      get_registry, parse_prometheus_text,
                      reset_registry)
from .trace import annotate, current_regions  # noqa: F401
from .flight import FlightRecorder, dump as dump_flight_record, \
    get_recorder  # noqa: F401
from . import flops  # noqa: F401
from . import commledger  # noqa: F401
from . import fleet  # noqa: F401
from . import goodput  # noqa: F401
from . import healthmon  # noqa: F401
from . import memledger  # noqa: F401
from . import moestats  # noqa: F401
from . import spans  # noqa: F401
from . import timeseries  # noqa: F401
from .commledger import CommLedger  # noqa: F401
from .fleet import FleetCollector  # noqa: F401
from .goodput import GoodputLedger  # noqa: F401
from .healthmon import HealthMonitor  # noqa: F401
from .memledger import MemLedger, RooflineReport, StateAccounting  # noqa: F401,E501
from .spans import (RequestTrace, SpanRing, format_traceparent,  # noqa: F401
                    make_span_id, make_trace_id, parse_traceparent)
from .timeseries import MetricsSampler  # noqa: F401
from .exporter import MetricsServer, serve_metrics  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "JsonlSink",
    "DEFAULT_LATENCY_BUCKETS", "get_registry", "reset_registry",
    "parse_prometheus_text", "annotate", "current_regions",
    "FlightRecorder", "dump_flight_record", "get_recorder", "flops",
    "cross_host_sum", "commledger", "CommLedger", "fleet",
    "FleetCollector", "goodput", "GoodputLedger", "healthmon",
    "HealthMonitor", "memledger", "MemLedger", "RooflineReport",
    "StateAccounting", "moestats", "spans", "RequestTrace", "SpanRing",
    "make_trace_id", "make_span_id", "format_traceparent",
    "parse_traceparent", "timeseries", "MetricsSampler",
    "MetricsServer", "serve_metrics",
]


def cross_host_sum(value: float) -> float:
    """Sum a host-local scalar across every process (rank 0 reports
    pod-level throughput). Single-process: identity. Multi-process:
    ``multihost_utils.process_allgather`` (an all_gather over hosts) —
    call BETWEEN steps only; it synchronizes all processes."""
    import jax

    if jax.process_count() == 1:
        return float(value)
    import numpy as np
    from jax.experimental import multihost_utils as mh

    return float(np.sum(mh.process_allgather(np.asarray(float(value)))))
