"""MoE routing telemetry: the trace-time collector MoELayer records
into and ParallelEngine drains into compiled-step outputs.

Expert-load / token-drop / aux-loss values are TRACED arrays computed
inside the compiled step (``MoELayer.forward``'s non-differentiated
stats aux). They cannot be fetched mid-trace, so the flow is:

1. the engine ``begin()``s a collection before calling the loss fn,
2. each MoELayer forward ``record()``s its stats dict (layer order =
   call order, stable per compiled program),
3. the engine ``drain()``s the list, psums the token counts over the
   batch-sharding axes, and returns the dict as an extra (replicated)
   step output,
4. the fetched host values feed the ``paddle_tpu_moe_*`` gauges with
   the same one-step lag as loss/grad-norm (catalog.train_metrics).

When no collection is active (eager forwards, serving, the pipelined
path — whose stage-masked scan would record misleading values),
``record()`` is a no-op, so MoE layers stay usable everywhere.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

__all__ = ["begin", "record", "drain", "active", "publish"]


class _State(threading.local):
    def __init__(self):
        self.records: Optional[List[Dict[str, Any]]] = None


_state = _State()


def active() -> bool:
    return _state.records is not None


def begin() -> None:
    """Start collecting (engine, just before tracing the loss fn)."""
    _state.records = []


def record(stats: Dict[str, Any]) -> None:
    """Append one MoE layer's routing stats (no-op unless a collection
    is active on this thread)."""
    if _state.records is not None:
        _state.records.append(stats)


def drain() -> List[Dict[str, Any]]:
    """End the collection and return the per-layer stats in call
    order."""
    recs, _state.records = _state.records, None
    return recs or []


def publish(fetched: Dict[str, Dict[str, Any]],
            metrics: Dict[str, Any]) -> None:
    """Feed fetched host values into the moe_* gauges.

    ``fetched``: {layer_label: {"load": [E] array, "routed": scalar,
    "dropped": scalar, "aux": scalar}} — the engine's extra step output
    after device fetch.
    """
    import numpy as np

    for layer, st in fetched.items():
        load = np.asarray(st["load"], dtype=np.float64)
        total = float(load.sum())
        for e in range(load.shape[0]):
            # fraction of routed-and-kept tokens landing on expert e:
            # uniform routing reads 1/E on every series
            metrics["moe_expert_load"].set(
                float(load[e]) / total if total > 0 else 0.0,
                layer=layer, expert=str(e))
        routed = float(np.asarray(st["routed"]))
        dropped = float(np.asarray(st["dropped"]))
        metrics["moe_drop_rate"].set(
            dropped / routed if routed > 0 else 0.0, layer=layer)
        metrics["moe_aux_loss"].set(float(np.asarray(st["aux"])),
                                    layer=layer)
