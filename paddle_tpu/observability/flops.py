"""Flop accountant: model FLOPs/token + MFU from the model config.

MFU follows the PaLM/Megatron convention (PAPERS.md: Megatron-LM): a
decoder-only transformer spends ~6*N FLOPs per token (fwd 2N + bwd 4N),
optionally plus the attention term 12*L*h*S that 6N omits; recompute
FLOPs are deliberately EXCLUDED so remat lowers measured MFU honestly
(the bench.py convention). The accountant reads whatever config the
model carries (GPTConfig / LlamaConfig expose ``num_params()``); when
there is no config it falls back to summing parameter sizes, which the
engine can always do.
"""
from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["params_from_config", "train_flops_per_token",
           "peak_flops_per_chip", "mfu", "ici_bytes_per_sec",
           "comm_seconds_lower_bound"]

# Peak dense bf16 FLOPs and HBM bandwidth per chip by TPU generation
# (public specs — the same table bench.py uses for its roofline lines).
PEAK_BY_CHIP = {
    "v4": (275e12, 1.2e12),
    "v5e": (197e12, 0.819e12), "v5 lite": (197e12, 0.819e12),
    "v5litepod": (197e12, 0.819e12),
    "v5p": (459e12, 2.765e12),
    "v6e": (918e12, 1.64e12), "v6 lite": (918e12, 1.64e12),
}

# Aggregate ICI bandwidth per chip (bytes/s, public specs: v4 2400
# Gbps, v5e 1600, v5p 4800, v6e 3584 — all links, both directions).
# The comm floor below uses it to turn ledger wire bytes into a
# lower-bound transfer time, contextualizing exposed-comm seconds.
ICI_BY_CHIP = {
    "v4": 300e9,
    "v5e": 200e9, "v5 lite": 200e9, "v5litepod": 200e9,
    "v5p": 600e9,
    "v6e": 448e9, "v6 lite": 448e9,
}


def ici_bytes_per_sec(device) -> float:
    """Aggregate ICI bytes/s of a jax device's chip generation; 0.0 on
    CPU (no ICI — comm floors are then reported as 0, well-defined)."""
    kind = str(getattr(device, "device_kind", "")).lower()
    for k, v in ICI_BY_CHIP.items():
        if k in kind:
            return v
    if "tpu" in str(getattr(device, "platform", "")).lower():
        return ICI_BY_CHIP["v5p"]    # unknown generation: assume v5p
    return 0.0


def comm_seconds_lower_bound(wire_bytes: float, device) -> float:
    """Analytic floor for moving ``wire_bytes`` (per participant, the
    comm ledger's closed-form accounting) over ICI: bytes / aggregate
    per-chip bandwidth. The per-bucket grad-sync attribution divides a
    step's ledger bytes by this to sanity-check exposed-comm numbers:
    exposed seconds below the floor mean the collective overlapped."""
    bw = ici_bytes_per_sec(device)
    if bw <= 0:
        return 0.0
    return float(wire_bytes) / bw


def params_from_config(config) -> Optional[int]:
    """Parameter count from a model config, or None (configs across the
    model zoo expose ``num_params()``; anything else is ignored)."""
    fn = getattr(config, "num_params", None)
    if callable(fn):
        try:
            return int(fn())
        except Exception:
            return None
    return None


def train_flops_per_token(n_params: int, *, config=None,
                          with_attention: bool = True) -> float:
    """~FLOPs one training token costs: 6*N plus (when the config
    exposes layer geometry) the 12*L*h*S attention-matmul term."""
    f = 6.0 * n_params
    if with_attention and config is not None:
        L = getattr(config, "num_layers", None)
        h = getattr(config, "hidden_size", None)
        S = getattr(config, "max_position_embeddings", None)
        if L and h and S:
            f += 12.0 * L * h * S
    return f


def peak_flops_per_chip(device) -> Tuple[float, float]:
    """(peak dense bf16 FLOPs/s, HBM bytes/s) for a jax device; (0, 0)
    on CPU, where MFU is not meaningful."""
    kind = str(getattr(device, "device_kind", "")).lower()
    for k, v in PEAK_BY_CHIP.items():
        if k in kind:
            return v
    if "tpu" in str(getattr(device, "platform", "")).lower():
        return PEAK_BY_CHIP["v5p"]   # unknown generation: assume v5p
    return (0.0, 0.0)


def mfu(n_params: int, tokens_per_sec: float, n_devices: int,
        peak_per_chip: float, *, config=None) -> float:
    """Model-FLOPs utilization of the whole slice; 0.0 when peak is
    unknown (CPU) so gauges stay well-defined everywhere."""
    denom = peak_per_chip * max(n_devices, 1)
    if denom <= 0:
        return 0.0
    return train_flops_per_token(n_params, config=config) \
        * tokens_per_sec / denom
