"""HBM memory ledger: per-executable memory attribution, model-state
accounting, and roofline bottleneck verdicts.

The comm ledger (commledger.py) made bytes-on-wire a first-class,
per-program fact; this module does the same for HBM, in three layers:

1. **Executable ledger** (``analyze`` -> ``MemLedger``): XLA's own
   buffer assignment, read through
   ``jax.stages.Compiled.memory_analysis()`` — temp / argument /
   output / alias / generated-code bytes of ONE compiled program,
   per device (SPMD executables share one module, so the numbers are
   what each chip's HBM actually holds). The engines store a ledger
   per compiled program next to its comm ledger and publish it as the
   ``paddle_tpu_mem_*_bytes{program}`` gauges. Analysis re-lowers the
   SAME jitted program AOT (an extra trace + XLA compile, once per
   program), so it is knob-gated: ``ParallelEngine(...,
   mem_ledger=True)`` / ``ServingEngine(..., mem_ledger=True)`` or
   ``PADDLE_TPU_MEM_LEDGER=1`` for eager per-trace analysis; the
   ``memory_ledger()`` accessors compute on demand either way. The
   compiled-program cache is untouched — zero recompiles of the real
   step (asserted in tests/test_memledger.py).

2. **Model-state accounting** (``account_engine`` ->
   ``StateAccounting``): measured per-device bytes of params / grads /
   optimizer state / master weights, dtype-aware and sharding-aware —
   each array's contribution is its ADDRESSABLE SHARD size
   (``sharding.shard_shape``), so ZeRO-scattered optimizer state,
   tp/pp-sharded params, and the pp x vpp stacked-chunk ownership all
   count at what one chip really stores. An analytic
   activation-checkpoint term (tokens_per_microbatch x hidden x
   local_layers x dtype) rides along, and the whole total is
   cross-checked against the auto_tuner's analytic model
   (distributed/auto_tuner/cost_model.estimate_memory_gb) with the
   relative drift reported as ``paddle_tpu_mem_analytic_drift`` — the
   gauge that finally validates the tuner's ``hbm_gb`` pruning against
   reality. ``closed_form_state_bytes`` recomputes params/state from
   GLOBAL shapes divided by sharding degrees (an independent
   derivation) for the exact parity gates — including ZeRO stage-3
   shard-only parameter storage, whose params component must land at
   exactly 1/sharding_degree of the replicated image (the
   ``gpt13b_hybrid_stage3_mem_state_parity`` bench gate).

3. **Roofline verdict** (``roofline`` -> ``RooflineReport``): joins
   the flop accountant (flops.py peak tables), the comm ledger (wire
   bytes / exposed seconds), and the memory ledger into a per-step
   bottleneck verdict: t_compute = FLOPs/peak, t_hbm = traffic/BW
   (traffic estimated as argument + output + 2 x temp bytes: args read
   once, outputs written once, temps written and read), t_ici =
   measured exposed-comm seconds (falling back to wire_bytes/ICI-BW).
   The largest term names the bound — compute-bound / hbm-bound /
   ici-bound — and every resource gets a headroom percentage
   ``100 * (1 - t_r / t_bound)``. On CPU all peaks are unknown, every
   term is 0 and the verdict is "unknown" (well-defined everywhere,
   the flops.py convention).

Live-bytes watermarks (``live_bytes``) sum every live ``jax.Array``'s
addressable shards — the step-boundary peak gauge on backends without
``memory_stats`` (the CPU harness). ``suggest_pool_pages`` turns the
measured headroom into serving page-pool sizing
(ServingEngine ``pool_pages="auto"``).

Everything here is host-side bookkeeping on shapes, dtypes and
shardings; nothing adds ops to any compiled program.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "MemLedger", "analyze", "shard_bytes", "StateAccounting",
    "account_engine", "closed_form_state_bytes", "RooflineReport",
    "roofline", "live_bytes", "suggest_pool_pages", "RESOURCES",
]

# the three roofline resources, in verdict tie-break order (a tie goes
# to the earlier entry: compute beats hbm beats ici)
RESOURCES = ("compute", "hbm", "ici")


# ---------------------------------------------------------------------------
# 1. per-executable memory ledger (XLA buffer assignment)
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class MemLedger:
    """Static memory attribution of ONE compiled executable, per device.

    Byte classes (XLA buffer assignment, ``memory_analysis()``):

    - ``argument_bytes``: input buffers the executable reads (params,
      optimizer state, the batch) — resident before the step runs,
    - ``output_bytes``: result buffers it writes (updated params/state,
      the loss) — resident after,
    - ``alias_bytes``: bytes shared between the two by donation
      (``donate_argnums`` buffer aliasing — the ZeRO-style in-place
      update; counted in BOTH argument and output, so peak subtracts
      it once),
    - ``temp_bytes``: scratch the program peaks through mid-step
      (activations, remat windows, collective staging),
    - ``generated_code_bytes``: the executable's own code + constants.
    """

    program: str = ""
    temp_bytes: int = 0
    argument_bytes: int = 0
    output_bytes: int = 0
    alias_bytes: int = 0
    generated_code_bytes: int = 0
    available: bool = True
    note: str = ""

    @property
    def peak_bytes(self) -> int:
        """Estimated HBM high-water mark of one execution: arguments +
        outputs + temps + code, minus the donation-aliased bytes that
        argument and output both count."""
        return (self.argument_bytes + self.output_bytes
                + self.temp_bytes + self.generated_code_bytes
                - self.alias_bytes)

    @property
    def traffic_bytes(self) -> int:
        """Roofline HBM-traffic estimate for one execution: arguments
        read once + outputs written once + temps written AND read
        (2x). A deliberate lower-bound-flavored heuristic — fusion
        avoids re-reads, loops re-touch — but byte-proportional to the
        working set, which is what the verdict needs."""
        return (self.argument_bytes + self.output_bytes
                + 2 * self.temp_bytes)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "program": self.program,
            "temp_bytes": self.temp_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "alias_bytes": self.alias_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "peak_bytes": self.peak_bytes,
            "available": self.available,
            **({"note": self.note} if self.note else {}),
        }

    def publish(self, metrics: Dict[str, Any],
                program: Optional[str] = None) -> None:
        """Set the ``paddle_tpu_mem_*_bytes{program}`` catalog gauges
        (train_metrics / serving_metrics keys)."""
        if not self.available:
            return
        prog = program if program is not None else self.program
        metrics["mem_temp"].set(self.temp_bytes, program=prog)
        metrics["mem_argument"].set(self.argument_bytes, program=prog)
        metrics["mem_output"].set(self.output_bytes, program=prog)
        metrics["mem_alias"].set(self.alias_bytes, program=prog)
        metrics["mem_code"].set(self.generated_code_bytes, program=prog)

    def same_totals(self, other: "MemLedger") -> bool:
        """Byte-class equality (the recompile-stability check)."""
        return (self.temp_bytes == other.temp_bytes
                and self.argument_bytes == other.argument_bytes
                and self.output_bytes == other.output_bytes
                and self.alias_bytes == other.alias_bytes)


def analyze(fn, args=(), program: str = "") -> MemLedger:
    """Memory ledger of ``fn`` (a ``jax.jit``-wrapped callable) at the
    given example ``args``: lowers the program AOT and reads XLA's
    ``memory_analysis()``. The identical trace means the identical
    buffer assignment as the executed program; the extra XLA compile
    happens once per program (the callers cache per program key) and
    never touches the jit cache, so the live step's compile counters
    stay flat. Backends without the analysis (or a failed lowering)
    return an ``available=False`` ledger instead of raising — a dead
    analysis must not take the step down."""
    try:
        stats = fn.lower(*args).compile().memory_analysis()
    except Exception as e:  # noqa: BLE001 - observability must not raise
        return MemLedger(program=program, available=False,
                         note=f"{type(e).__name__}: {e}"[:200])
    if stats is None:
        return MemLedger(program=program, available=False,
                         note="memory_analysis unavailable")
    return MemLedger(
        program=program,
        temp_bytes=int(getattr(stats, "temp_size_in_bytes", 0)),
        argument_bytes=int(getattr(stats, "argument_size_in_bytes", 0)),
        output_bytes=int(getattr(stats, "output_size_in_bytes", 0)),
        alias_bytes=int(getattr(stats, "alias_size_in_bytes", 0)),
        generated_code_bytes=int(
            getattr(stats, "generated_code_size_in_bytes", 0)))


# ---------------------------------------------------------------------------
# 2. model-state accounting (measured, per device)
# ---------------------------------------------------------------------------
def shard_bytes(arr) -> int:
    """Bytes ONE device's addressable shard of ``arr`` occupies: the
    global shape run through ``sharding.shard_shape`` (replicated dims
    contribute fully, sharded dims their slice). Plain host / single-
    device arrays fall back to their full size."""
    shape = getattr(arr, "shape", None)
    if shape is None:
        return 0
    try:
        itemsize = int(np.dtype(arr.dtype).itemsize)
    except Exception:
        itemsize = int(getattr(getattr(arr, "dtype", None), "itemsize", 4))
    sh = getattr(arr, "sharding", None)
    if sh is not None:
        try:
            shape = sh.shard_shape(tuple(int(s) for s in shape))
        except Exception:
            pass
    return int(np.prod(shape)) * itemsize if len(shape) else itemsize


def _spec_degree(p, mesh, extra_axes=()) -> int:
    """Number of distinct shards a param's PartitionSpec (plus
    ``extra_axes``) splits it into — the closed-form divisor."""
    axes = set(extra_axes)
    da = getattr(p, "dist_attr", None)
    for ax in (tuple(da) if da is not None else ()):
        if isinstance(ax, (tuple, list)):
            axes.update(ax)
        elif ax is not None:
            axes.add(ax)
    deg = 1
    for a in axes:
        if a in mesh.axis_names:
            deg *= int(mesh.shape[a])
    return max(deg, 1)


def _group_name(name: str) -> str:
    """Layer-group key for the per-group breakdown: the first two
    dotted path components ("gpt.decoder", "lm_head", ...) — coarse on
    purpose; the stacked pp blocks live under one group whose bytes
    show the chunk ownership."""
    parts = name.split(".")
    return ".".join(parts[:2]) if len(parts) > 1 else name


@dataclass
class StateAccounting:
    """Measured per-device model-state footprint + the analytic drift.

    ``components``: params / grads / optimizer_state / master_weights /
    activation_ckpt bytes one device holds. Grads are transient (alive
    between backward and update) and counted at the param's
    PartitionSpec shard size; activation_ckpt is the analytic
    checkpoint-boundary term (see ``account_engine``). ``groups`` is
    the per-layer-group breakdown of the persistent classes.
    ``analytic_bytes`` is the auto_tuner cost model's estimate for the
    same config; ``drift`` = (analytic - measured) / measured.
    """

    components: Dict[str, int] = field(default_factory=dict)
    groups: Dict[str, Dict[str, int]] = field(default_factory=dict)
    analytic_bytes: float = 0.0
    drift: float = 0.0

    @property
    def measured_bytes(self) -> int:
        return int(sum(self.components.values()))

    @property
    def device_bytes(self) -> int:
        """HBM-resident bytes only: the measured total minus the
        ``host_state`` component (state the offload tier holds in host
        memory between steps). What the analytic ``hbm_gb`` pruning —
        and the bench's stage-3-minus-offloaded parity line — compare
        against."""
        return self.measured_bytes - int(
            self.components.get("host_state", 0))

    def to_dict(self) -> Dict[str, Any]:
        return {
            "components": dict(self.components),
            "groups": {g: dict(v) for g, v in sorted(self.groups.items())},
            "measured_bytes": self.measured_bytes,
            "device_bytes": self.device_bytes,
            "analytic_bytes": round(self.analytic_bytes, 1),
            "analytic_drift": round(self.drift, 4),
        }

    def publish(self, metrics: Dict[str, Any]) -> None:
        for comp, v in self.components.items():
            metrics["mem_state"].set(v, component=comp)
        metrics["mem_drift"].set(self.drift)


def _mesh_degree(mesh, axis: str) -> int:
    return int(mesh.shape[axis]) if axis in mesh.axis_names else 1


def account_engine(engine, batch_tokens: int = 0,
                   accumulate_steps: int = 1) -> StateAccounting:
    """Measured model-state accounting of a ``ParallelEngine``:
    addressable-shard bytes of every param / optimizer-state / master-
    weight array (ZeRO scatter, tp/pp sharding and the pp x vpp stacked
    chunks all already live in the arrays' shardings), plus the
    analytic activation-checkpoint term and the auto_tuner cross-check.

    ``batch_tokens`` is the host-local tokens one step consumes (the
    engine's ``_batch_tokens``); ``accumulate_steps`` the microbatch
    count — together they size the checkpoint term:
    ``local_layers x tokens_per_microbatch_per_rank x hidden x
    dtype_bytes`` (one saved residual per transformer block, the
    remat-boundary convention; reported 0 when the model carries no
    layer-geometry config)."""
    from ..distributed.host_offload import is_host

    mesh = engine.mesh
    opt = engine.optimizer
    comp = {"params": 0, "grads": 0, "optimizer_state": 0,
            "master_weights": 0, "activation_ckpt": 0}
    # host-offloaded state (distributed/host_offload.py): slots the
    # tier holds as HostState between steps book under ONE host_state
    # component at the SAME per-device shard size (HostState exposes
    # the live sharding, so shard_bytes prices it identically) — the
    # device components shrink by exactly what host_state gains,
    # byte-for-byte (the bench offload parity line gates on it)
    host = 0
    # quant_comm error-feedback residuals are REAL HBM: one f32
    # bucket-payload-sized buffer per quantizing bucket (engine
    # _quant_residuals; the analytic model's quant_comm term mirrors
    # this so paddle_tpu_mem_analytic_drift stays honest)
    qres = getattr(engine, "_quant_residuals", None) or {}
    if qres:
        dev_q = sum(shard_bytes(v) for v in qres.values()
                    if not is_host(v))
        host += sum(shard_bytes(v) for v in qres.values()
                    if is_host(v))
        if dev_q:
            comp["quant_residual"] = dev_q
    groups: Dict[str, Dict[str, int]] = {}
    named = {}
    try:
        named = {id(p): n for n, p in engine.model.named_parameters()}
    except Exception:
        pass
    states = getattr(opt, "_states", {}) if opt is not None else {}
    masters = getattr(opt, "_master_weights", {}) if opt is not None \
        else {}
    for p in engine.params:
        pb = shard_bytes(p._value)
        if is_host(p._value):
            host += pb
        else:
            comp["params"] += pb
        g = groups.setdefault(_group_name(named.get(id(p), "param")),
                              {"params": 0, "optimizer_state": 0,
                               "master_weights": 0})
        g["params"] += pb
        if getattr(p, "trainable", True):
            # transient backward grads live at the param's spec shard
            # (before any ZeRO scatter); dtype follows the param. For
            # stage-3 stored-sharded params pb is already the 1/sh
            # scatter shard — matching the cost model's grad_bytes/sh
            # (the eager per-bucket scatter keeps full grads transient
            # at bucket grain), so the analytic drift stays flat when
            # the stage knob flips. Grads are device-transient even
            # when the param shard itself is host-offloaded.
            comp["grads"] += pb
        st = states.get(id(p))
        if st:
            sb = sum(shard_bytes(v) for v in st.values()
                     if hasattr(v, "shape") and not is_host(v))
            hb = sum(shard_bytes(v) for v in st.values()
                     if is_host(v))
            comp["optimizer_state"] += sb
            host += hb
            g["optimizer_state"] += sb + hb
        mw = masters.get(id(p))
        if mw is not None:
            mb = shard_bytes(mw)
            if is_host(mw):
                host += mb
            else:
                comp["master_weights"] += mb
            g["master_weights"] += mb
    if host:
        comp["host_state"] = host

    cfg = getattr(engine.model, "config", None)
    hidden = getattr(cfg, "hidden_size", None)
    layers = getattr(cfg, "num_layers", None)
    analytic = 0.0
    if hidden and layers:
        dtype_bytes = int(np.dtype(engine.params[0]._value.dtype).itemsize
                          if engine.params else 4)
        pp = _mesh_degree(mesh, "pp")
        mp = _mesh_degree(mesh, "mp")
        data_deg = 1
        for a in ("dp", "sharding", "ep"):
            data_deg *= _mesh_degree(mesh, a)
        micro_tokens = batch_tokens / max(data_deg * accumulate_steps, 1)
        comp["activation_ckpt"] = int(
            (layers / max(pp, 1)) * micro_tokens * hidden * dtype_bytes)
        # the auto_tuner's analytic model for the same config (its
        # pruning input, now validated against the measured total).
        # seq_len carries the whole tokens-per-microbatch-per-rank
        # product with micro_batch_size pinned to 1 — the model only
        # ever uses micro x seq_len x hidden.
        from ..distributed.auto_tuner.cost_model import \
            estimate_memory_gb

        zero = getattr(engine, "_zero", None)
        sh_deg = getattr(zero, "n", 1) if getattr(zero, "axis", None) \
            else 1
        stage3 = any(e[1] for e in zero.entries.values()) \
            if zero is not None and zero.entries else False
        model_d = {"hidden_size": hidden, "num_layers": layers,
                   "vocab_size": getattr(cfg, "vocab_size", 50304)}
        cfg_d = {"dp_degree": _mesh_degree(mesh, "dp"),
                 "mp_degree": mp, "pp_degree": pp,
                 "sharding_degree": sh_deg,
                 "sharding_stage": 3 if stage3 else 2,
                 "micro_batch_size": 1}
        qcfg = getattr(engine, "_quant_cfg", None)
        if qres and qcfg is not None and qcfg.enabled:
            cfg_d["quant_comm"] = {"dtype": qcfg.dtype,
                                   "error_feedback": True}
        # the offload knob flows into the cost model so the analytic
        # estimate prices the same HBM image the engine actually holds
        # (estimate_memory_gb subtracts the host-tier classes) and the
        # drift gauge stays flat when the knob flips
        tier = getattr(engine, "_offload", None)
        if tier is not None:
            cfg_d["offload"] = {"optimizer": tier.cfg.optimizer,
                                "params": tier.cfg.params}
        try:
            analytic = estimate_memory_gb(
                model_d, cfg_d,
                global_batch=max(data_deg * accumulate_steps, 1),
                seq_len=max(int(micro_tokens), 1),
                dtype_bytes=dtype_bytes) * 1e9
        except Exception:
            analytic = 0.0
    # drift compares DEVICE-resident bytes: the analytic model prices
    # HBM, and host_state is precisely what HBM no longer holds
    measured = sum(comp.values()) - comp.get("host_state", 0)
    drift = ((analytic - measured) / measured) if measured and analytic \
        else 0.0
    return StateAccounting(components=comp, groups=groups,
                           analytic_bytes=analytic, drift=drift)


def closed_form_state_bytes(engine) -> Dict[str, int]:
    """Closed-form per-device param / optimizer / master-weight bytes:
    GLOBAL shapes divided by the sharding degrees the specs + ZeRO plan
    declare — an independent derivation from ``account_engine`` (which
    reads ``sharding.shard_shape``); the two must agree exactly, which
    the bench parity lines and tests/test_memledger.py gate on.

    With the host-offload tier active, bytes the tier holds on host
    (per the knob: optimizer moments + masters, optionally param
    shards) move into a ``host_state`` key — still derived purely from
    GLOBAL shapes and degrees, so the byte-for-byte cross-check covers
    the offloaded split too."""
    from ..distributed.host_offload import is_host

    mesh = engine.mesh
    opt = engine.optimizer
    zero = getattr(engine, "_zero", None)
    tier = getattr(engine, "_offload", None)
    off_opt = tier is not None and tier.cfg.optimizer
    off_par = tier is not None and tier.cfg.params
    out = {"params": 0, "optimizer_state": 0, "master_weights": 0}
    host = 0
    for p in engine.params:
        nbytes = int(np.prod(p._value.shape) if p._value.ndim else 1) \
            * int(np.dtype(p._value.dtype).itemsize)
        e = zero.entry(p) if zero is not None else None
        # stage-3 params are STORED scattered; stage 1/2 replicated
        store_extra = (zero.axis,) if e is not None and e[1] else ()
        pb = nbytes // _spec_degree(p, mesh, store_extra)
        # the tier only moves a slot it actually adopted (a live
        # HostState) — a freshly-built engine before the first
        # page-out still accounts fully on device
        if off_par and is_host(p._value):
            host += pb
        else:
            out["params"] += pb
        if not getattr(p, "trainable", True) or opt is None:
            continue
        state_extra = (zero.axis,) if e is not None else ()
        st = getattr(opt, "_states", {}).get(id(p), {})
        for v in st.values():
            if not hasattr(v, "shape"):
                continue
            vb = int(np.prod(v.shape) if v.ndim else 1) \
                * int(np.dtype(v.dtype).itemsize)
            if tuple(v.shape) == tuple(p._value.shape):
                vb //= _spec_degree(p, mesh, state_extra)
            if off_opt and is_host(v):
                host += vb
            else:
                out["optimizer_state"] += vb
        mw = getattr(opt, "_master_weights", {}).get(id(p))
        if mw is not None:
            mb = int(np.prod(mw.shape) if mw.ndim else 1) \
                * int(np.dtype(mw.dtype).itemsize)
            mb //= _spec_degree(p, mesh, state_extra)
            if off_opt and is_host(mw):
                host += mb
            else:
                out["master_weights"] += mb
    if off_opt:
        # quant-comm EF residuals ride the optimizer class: dim 0 is
        # sharded over EVERY >1 mesh axis, so the per-device closed
        # form is the global size over the full mesh product
        prod = 1
        for a in mesh.axis_names:
            if int(mesh.shape[a]) > 1:
                prod *= int(mesh.shape[a])
        for v in getattr(engine, "_quant_residuals", {}).values():
            if is_host(v):
                vb = int(np.prod(v.shape) if v.ndim else 1) \
                    * int(np.dtype(v.dtype).itemsize)
                host += vb // prod
    if host:
        out["host_state"] = host
    return out


# ---------------------------------------------------------------------------
# 3. roofline verdict
# ---------------------------------------------------------------------------
@dataclass
class RooflineReport:
    """The per-step bottleneck verdict.

    ``seconds[r]`` is the analytic floor each resource needs for one
    step (compute: FLOPs/peak; hbm: traffic/BW; ici: measured exposed
    comm, else wire-bytes/BW). ``bound`` names the largest —
    compute-bound / hbm-bound / ici-bound — or "unknown" when every
    peak is unknown (CPU). ``headroom_pct[r]`` = 100 x (1 - t_r /
    t_bound): 0 for the binding resource, how far the others sit below
    it. ``util_pct[r]`` = 100 x t_r / step_seconds when a measured
    step time is known (how much of the real step each floor explains;
    the gap to 100 across ALL resources is dispatch/bubble overhead).
    """

    program: str = ""
    step_seconds: float = 0.0
    seconds: Dict[str, float] = field(default_factory=dict)
    bound: str = "unknown"
    headroom_pct: Dict[str, float] = field(default_factory=dict)
    util_pct: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "program": self.program,
            "bound": self.bound,
            "step_seconds": round(self.step_seconds, 6),
            "seconds": {k: round(v, 6) for k, v in self.seconds.items()},
            "headroom_pct": {k: round(v, 2)
                             for k, v in self.headroom_pct.items()},
            "util_pct": {k: round(v, 2)
                         for k, v in self.util_pct.items()},
        }


def roofline(*, step_seconds: float, flops_per_step: float,
             hbm_traffic_bytes: float, wire_bytes: float = 0.0,
             device=None, exposed_ici_seconds: Optional[float] = None,
             program: str = "") -> RooflineReport:
    """Assemble the roofline verdict from per-chip quantities:
    ``flops_per_step`` / ``hbm_traffic_bytes`` / ``wire_bytes`` are
    one chip's share (the comm ledger's per-participant convention);
    ``exposed_ici_seconds`` is the measured exposed-comm total when a
    profile_exposed_comm report exists (preferred over the analytic
    wire floor, which assumes zero overlap credit)."""
    from . import flops as _flops

    peak, hbm_bw = _flops.peak_flops_per_chip(device) if device \
        is not None else (0.0, 0.0)
    ici_bw = _flops.ici_bytes_per_sec(device) if device is not None \
        else 0.0
    t = {
        "compute": (flops_per_step / peak) if peak > 0 else 0.0,
        "hbm": (hbm_traffic_bytes / hbm_bw) if hbm_bw > 0 else 0.0,
        "ici": (float(exposed_ici_seconds)
                if exposed_ici_seconds is not None
                else ((wire_bytes / ici_bw) if ici_bw > 0 else 0.0)),
    }
    t = {k: max(v, 0.0) for k, v in t.items()}
    rep = RooflineReport(program=program,
                         step_seconds=max(float(step_seconds), 0.0),
                         seconds=t)
    # a verdict needs the chip's peak tables: on CPU (all peaks
    # unknown) one measured ici term must not be crowned "the bound"
    # over floors that are simply unknowable — stay "unknown"
    peaks_known = peak > 0 or hbm_bw > 0 or ici_bw > 0
    t_bound = max(t.values())
    if peaks_known and t_bound > 0:
        rep.bound = next(r for r in RESOURCES if t[r] == t_bound) \
            + "-bound"
        rep.headroom_pct = {r: 100.0 * (1.0 - t[r] / t_bound)
                            for r in RESOURCES}
    else:
        rep.headroom_pct = {r: 0.0 for r in RESOURCES}
    if rep.step_seconds > 0:
        rep.util_pct = {r: 100.0 * t[r] / rep.step_seconds
                        for r in RESOURCES}
    return rep


# ---------------------------------------------------------------------------
# live-bytes watermark + page-pool sizing
# ---------------------------------------------------------------------------
def live_bytes() -> int:
    """Total device bytes held by live ``jax.Array``s in this process
    (every array's shard size times its addressable-device count) —
    the step-boundary watermark source on backends without
    ``memory_stats`` (the CPU harness). Best-effort: 0 on failure."""
    try:
        import jax

        total = 0
        for a in jax.live_arrays():
            sh = getattr(a, "sharding", None)
            n_dev = len(sh.addressable_devices) if sh is not None else 1
            total += shard_bytes(a) * n_dev
        return int(total)
    except Exception:
        return 0


def suggest_pool_pages(device, page_bytes: int, reserved_bytes: int,
                       margin: float = 0.1) -> Optional[int]:
    """Size a serving KV page pool from measured HBM headroom:
    ``(bytes_limit x (1 - margin) - reserved_bytes) / page_bytes``
    pages, where ``reserved_bytes`` is what the model already holds
    (params; ``account_engine``-style shard bytes). Returns ``None``
    when the backend exposes no ``bytes_limit`` (CPU) or nothing fits
    — the caller falls back to its geometric default."""
    if page_bytes <= 0:
        return None
    try:
        stats = device.memory_stats() or {}
    except Exception:
        return None
    limit = int(stats.get("bytes_limit", 0))
    if limit <= 0:
        return None
    usable = int(limit * (1.0 - margin)) - int(reserved_bytes)
    if usable < page_bytes:
        return None
    return int(usable // page_bytes)
